"""Unique name generation (reference: python/paddle/fluid/unique_name.py,
re-exported as paddle.utils.unique_name).

Same contract: a process-wide generator keyed by prefix, switchable and
guardable for isolated name scopes (program capture, tests).
"""
from __future__ import annotations

import contextlib

__all__ = ["generate", "switch", "guard"]


class UniqueNameGenerator:
    def __init__(self, prefix=None):
        self._ids = {}
        self._prefix = prefix or ""

    def __call__(self, key):
        i = self._ids.get(key, 0)
        self._ids[key] = i + 1
        return "_".join([self._prefix + key, str(i)]) if self._prefix \
            else f"{key}_{i}"


_generator = UniqueNameGenerator()


def generate(key):
    """`key` -> "key_N" with a process-unique N per key."""
    return _generator(key)


def switch(new_generator=None):
    """Replace the active generator; returns the previous one."""
    global _generator
    old = _generator
    _generator = new_generator or UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Scope with a fresh (or given) generator; restores on exit."""
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
