"""JIT C++ extension builder/loader.

Reference: python/paddle/utils/cpp_extension/cpp_extension.py:736 (`load`)
and :51/:207 (`setup`/`CppExtension`). TPU-native design: no pybind11 in the
image, so extensions expose a plain C ABI and load through ctypes — the
calls drop the GIL, which is exactly what the input-pipeline C++ (csrc/)
needs. Builds shared objects with g++, content-hash cached so repeat loads
are instant.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sysconfig
import threading

__all__ = ["load", "get_build_directory", "CppExtension", "CUDAExtension",
           "setup"]

_DEFAULT_CFLAGS = ["-O3", "-std=c++17", "-fPIC", "-shared", "-pthread"]


def get_build_directory():
    d = os.environ.get(
        "PADDLE_EXTENSION_DIR",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


def _content_hash(sources, flags):
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(flags).encode())
    return h.hexdigest()[:16]


def load(name, sources, extra_cxx_cflags=None, extra_cuda_cflags=None,
         extra_ldflags=None, extra_include_paths=None,
         build_directory=None, interpreter=None, verbose=False):
    """Compile `sources` into <name>.so (cached by content hash) and return
    the ctypes.CDLL handle. Mirrors the reference's JIT `load` entry point,
    minus CUDA (extra_cuda_cflags accepted and ignored on TPU hosts)."""
    sources = [os.path.abspath(s) for s in sources]
    for s in sources:
        if not os.path.exists(s):
            raise FileNotFoundError(s)
    build_dir = build_directory or get_build_directory()
    flags = list(_DEFAULT_CFLAGS)
    flags += extra_cxx_cflags or []
    for inc in (extra_include_paths or []):
        flags.append(f"-I{inc}")
    tag = _content_hash(sources, flags)
    out = os.path.join(build_dir, f"{name}_{tag}.so")
    if not os.path.exists(out):
        # pid+thread-unique temp: concurrent builders (pytest-xdist, two
        # procs, two threads) must not scribble on each other's object
        tmp = f"{out}.tmp.{os.getpid()}.{threading.get_ident()}"
        cmd = ["g++"] + flags + sources + ["-o", tmp] + (extra_ldflags or [])
        if verbose:
            print("cpp_extension:", " ".join(cmd))
        try:
            subprocess.run(cmd, check=True, capture_output=not verbose)
            os.replace(tmp, out)
        except subprocess.CalledProcessError as e:
            stderr = (e.stderr or b"").decode(errors="replace")
            raise RuntimeError(
                f"building extension '{name}' failed:\n{stderr}") from e
        finally:
            if os.path.exists(tmp):  # orphan from a failed compile
                os.remove(tmp)
    return ctypes.CDLL(out)


# ---- setuptools-style surface (reference cpp_extension.py:51/:207) --------
def CppExtension(sources, *args, **kwargs):
    from setuptools import Extension

    kwargs.setdefault("language", "c++")
    extra = kwargs.pop("extra_compile_args", None) or []
    if isinstance(extra, dict):
        extra = extra.get("cxx", [])
    kwargs["extra_compile_args"] = ["-std=c++17"] + list(extra)
    kwargs.setdefault("include_dirs", []).append(
        sysconfig.get_paths()["include"])
    name = kwargs.pop("name", "paddle_tpu_ext")
    return Extension(name, sources, *args, **kwargs)


def CUDAExtension(sources, *args, **kwargs):
    # no CUDA toolchain on TPU hosts; build the C++ translation unit set
    sources = [s for s in sources if not s.endswith((".cu", ".cuh"))]
    return CppExtension(sources, *args, **kwargs)


def setup(**attr):
    from setuptools import setup as _setup

    ext = attr.pop("ext_modules", None)
    if ext is not None and not isinstance(ext, (list, tuple)):
        ext = [ext]
    attr["ext_modules"] = ext or []
    name = attr.get("name")
    if name is None and attr["ext_modules"]:
        attr["name"] = attr["ext_modules"][0].name
    return _setup(**attr)
