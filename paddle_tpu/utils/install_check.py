"""Installation self-check (reference: python/paddle/utils/
install_check.py:220 run_check — trains a tiny network in dygraph and
static mode and reports whether the install works).

TPU-native: the same two smoke flows on whatever backend jax resolved
(TPU chip under axon, CPU otherwise), plus a device report.
"""
from __future__ import annotations

__all__ = []


def _simple_network():
    import paddle_tpu as paddle
    from paddle_tpu import nn

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(16, 10)

        def forward(self, x):
            return self.fc(x)

    return Net()


def _run_dygraph_single():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn

    paddle.seed(0)
    model = _simple_network()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 16).astype("float32"))
    y = paddle.to_tensor(np.array([[0], [1], [2], [3]], dtype="int64"))
    loss = nn.functional.cross_entropy(model(x), y)
    loss.backward()
    opt.step()
    return float(loss)


def _run_static_single():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn

    was_dynamic = paddle.in_dynamic_mode()
    paddle.enable_static()
    try:
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [4, 16], "float32")
            y = paddle.static.data("y", [4, 1], "int64")
            logits = nn.Linear(16, 10)(x)
            loss = nn.functional.cross_entropy(logits, y)
        exe = paddle.static.Executor()
        rng = np.random.RandomState(0)
        (lv,) = exe.run(main,
                        feed={"x": rng.randn(4, 16).astype("float32"),
                              "y": np.array([[0], [1], [2], [3]],
                                            dtype="int64")},
                        fetch_list=[loss])
        return float(lv)
    finally:
        # restore the caller's mode — a user already in static mode must
        # not come back from a smoke check in dygraph mode
        if was_dynamic:
            paddle.disable_static()


def run_check():
    """Smoke-train in both execution modes and report (reference
    install_check.py:220)."""
    import jax

    backend = jax.default_backend()
    n = jax.device_count()
    print(f"Running verify PaddlePaddle(TPU) program ... "
          f"[backend={backend}, devices={n}]")
    dy = _run_dygraph_single()
    st = _run_static_single()
    assert dy == dy and st == st, "non-finite smoke losses"
    print("PaddlePaddle(TPU) works well on 1 device.")
    print("PaddlePaddle(TPU) is installed successfully! Let's start deep "
          "learning with PaddlePaddle(TPU) now.")
