"""Custom-op bridge: host C++/Python kernels as traced ops.

Reference: paddle/extension.h + python/paddle/utils/cpp_extension — custom
C++ ops registered into the op library, usable from dygraph and static
graph. TPU-native design: the kernel stays a host function (typically a
ctypes call into a cpp_extension .so); `jax.pure_callback` splices it into
the XLA program so it works under jit/vmap and inside hapi/static whole-step
programs, and an optional backward kernel is attached with jax.custom_vjp so
the op participates in the autograd tape.

Host callbacks do not run on the TPU — use this for ops that are genuinely
host-side (IO, CPU-only libraries, custom C++ data transforms), not for hot
compute (write a Pallas kernel for that).
"""
from __future__ import annotations

import jax
import numpy as np

from ..core.autograd import apply
from ..core.tensor import Tensor

__all__ = ["register_custom_op", "CustomOp"]


def _as_structs(shapes_dtypes):
    out = [jax.ShapeDtypeStruct(tuple(s), np.dtype(d))
           for s, d in shapes_dtypes]
    return out[0] if len(out) == 1 else tuple(out)


def _is_shape_dtype(sd):
    """One (shape, dtype) pair — incl. scalar shape () — vs a tuple of
    pairs for multi-output ops."""
    return (isinstance(sd, (tuple, list)) and len(sd) == 2
            and isinstance(sd[0], (tuple, list))
            and all(isinstance(i, (int, np.integer)) for i in sd[0])
            and not isinstance(sd[1], (tuple, list)))


class CustomOp:
    """A host kernel exposed as a Paddle-style traced op."""

    def __init__(self, name, forward, infer_shape, backward=None,
                 vectorized=False):
        self.name = name
        self._n_out = None

        def np_fwd(*arrays):
            res = forward(*[np.asarray(a) for a in arrays])
            return res if isinstance(res, tuple) else np.asarray(res)

        def jax_fn(*args):
            sd = infer_shape(*[(a.shape, a.dtype) for a in args])
            structs = _as_structs([sd] if _is_shape_dtype(sd) else sd)
            return jax.pure_callback(np_fwd, structs, *args,
                                     vmap_method="sequential")

        if backward is not None:
            def np_bwd(*arrays):
                res = backward(*[np.asarray(a) for a in arrays])
                return res if isinstance(res, tuple) else np.asarray(res)

            @jax.custom_vjp
            def op(*args):
                return jax_fn(*args)

            def fwd(*args):
                return jax_fn(*args), args

            def bwd(residual, ct):
                # input cotangents have the inputs' shapes/dtypes; multi-
                # output cotangents are passed as separate leading args
                structs = tuple(
                    jax.ShapeDtypeStruct(a.shape, a.dtype) for a in residual)
                cts = jax.tree_util.tree_leaves(ct)
                grads = jax.pure_callback(
                    np_bwd, structs[0] if len(structs) == 1 else structs,
                    *cts, *residual, vmap_method="sequential")
                return grads if isinstance(grads, tuple) else (grads,)

            op.defvjp(fwd, bwd)
            self._jax_fn = op
        else:
            self._jax_fn = jax_fn
        self._jax_fn.__name__ = name

    def __call__(self, *args):
        """Eager/tape entry: accepts Tensors, records a GradNode."""
        return apply(self._jax_fn, *args)

    @property
    def jax_fn(self):
        """Raw jax-level function for direct use inside jitted code."""
        return self._jax_fn


def register_custom_op(name, forward, infer_shape, backward=None):
    """Build a CustomOp.

    forward(*np_arrays) -> np array (or tuple): the host kernel — usually a
        thin wrapper over a ctypes call into a cpp_extension library.
    infer_shape(*(shape, dtype)) -> (shape, dtype) (or tuple of them).
    backward(*cotangents, *inputs) -> grads w.r.t. each input (optional);
        one leading cotangent argument per forward output.
    """
    return CustomOp(name, forward, infer_shape, backward=backward)
