"""Op version checkpoints (reference: python/paddle/utils/op_version.py:50
OpLastCheckpointChecker over the C++ op-version registry).

TPU-native: there is no PHI op registry — kernels are jax/XLA programs
versioned with the package. The checker keeps the reference's query API
over a python-side registry so tooling that inspects op compatibility
(model converters, save/load version gates) keeps working; entries can be
registered by ops that need migration notes.
"""
from __future__ import annotations

__all__ = []

_op_version_registry = {}  # op_name -> list of (note, version_id, type)


def register_op_version(op_name, note, version_id, update_type=None):
    _op_version_registry.setdefault(op_name, []).append(
        (note, version_id, update_type))


def Singleton(cls):
    insts = {}

    def get(*a, **kw):
        if cls not in insts:
            insts[cls] = cls(*a, **kw)
        return insts[cls]
    return get


class OpUpdateInfoHelper:
    def __init__(self, info):
        self._info = info

    def verify_key_value(self, name=""):
        return name == "" or name in str(self._info)


@Singleton
class OpLastCheckpointChecker:
    """Query the latest version checkpoint of an op (reference
    op_version.py:50)."""

    def __init__(self):
        self.checker = _op_version_registry

    def filter_updates(self, op_name, type=None, key=""):  # noqa: A002
        updates = []
        for note, _vid, utype in self.checker.get(op_name, []):
            if type is not None and utype != type:
                continue
            helper = OpUpdateInfoHelper(note)
            if helper.verify_key_value(key):
                updates.append(note)
        return updates
