"""Weight/file cache resolution (reference: python/paddle/utils/
download.py:75 get_weights_path_from_url, :121 get_path_from_url).

This build runs zero-egress: http(s) URLs resolve ONLY against the local
cache (a pre-populated ~/.cache/paddle/hapi/weights) and raise a loud
RuntimeError on a miss instead of downloading. file:// URLs and plain
paths are copied/decompressed into the cache, which keeps the decompress/
md5 pipeline of the reference exercised and lets users sideload weights.
"""
from __future__ import annotations

import hashlib
import os
import os.path as osp
import shutil
import tarfile
import zipfile

__all__ = ["get_weights_path_from_url"]

WEIGHTS_HOME = osp.expanduser("~/.cache/paddle/hapi/weights")
DOWNLOAD_RETRY_LIMIT = 3


def is_url(path):
    """Reference download.py:66 contract."""
    return path.startswith("http://") or path.startswith("https://") \
        or path.startswith("file://")


def _map_path(url, root_dir):
    fname = osp.split(url)[-1]
    return osp.join(root_dir, fname)


def _md5check(fullname, md5sum=None):
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            md5.update(chunk)
    return md5.hexdigest() == md5sum


def _decompress(fname):
    """Unpack zip/tar next to the archive; return the extraction root.
    Already-extracted archives (root present) are not re-extracted —
    hot-path resolutions must not rewrite files another reader may hold
    open (reference download.py:283 has the same check-then-extract).
    Multi-root archives extract into their own '<archive-stem>_unpacked'
    dir so the shared cache root never collects loose files."""
    dirname = osp.dirname(fname)
    if zipfile.is_zipfile(fname):
        with zipfile.ZipFile(fname) as z:
            names = z.namelist()
            root, dest = _roots(names, fname, dirname)
            if not osp.exists(root):
                z.extractall(dest)
    elif tarfile.is_tarfile(fname):
        with tarfile.open(fname) as t:
            names = t.getnames()
            root, dest = _roots(names, fname, dirname)
            if not osp.exists(root):
                t.extractall(dest, filter="data")
    else:
        return fname
    return root


def _roots(names, fname, dirname):
    """(extraction root to return/check, extractall destination)."""
    tops = {n.split("/")[0] for n in names if n.strip("/")}
    if len(tops) == 1:
        return osp.join(dirname, tops.pop()), dirname
    stem = osp.splitext(osp.basename(fname))[0] + "_unpacked"
    dest = osp.join(dirname, stem)
    return dest, dest


def get_path_from_url(url, root_dir=WEIGHTS_HOME, md5sum=None,
                      check_exist=True, decompress=True):
    """Resolve `url` to a local path under root_dir (reference
    download.py:121), without network egress."""
    os.makedirs(root_dir, exist_ok=True)
    if url.startswith("file://"):
        src = url[len("file://"):]
    elif not is_url(url):
        src = url  # plain local path
    else:
        src = None  # http(s): cache-only
    fullname = _map_path(url, root_dir)
    if osp.exists(fullname) and check_exist and _md5check(fullname, md5sum):
        pass  # cache hit
    elif src is not None:
        if not osp.exists(src):
            raise FileNotFoundError(f"{url}: local source {src} not found")
        shutil.copy(src, fullname)
        if not _md5check(fullname, md5sum):
            raise OSError(f"{fullname} md5 mismatch (expected {md5sum})")
    else:
        raise RuntimeError(
            f"cannot fetch {url}: this build runs with zero network "
            f"egress. Pre-place the file at {fullname} (or pass a "
            "file:// URL) — pretrained-weight downloads are not "
            "available on this deployment.")
    if decompress and (zipfile.is_zipfile(fullname)
                       or tarfile.is_tarfile(fullname)):
        return _decompress(fullname)
    return fullname


def get_weights_path_from_url(url, md5sum=None):
    """Reference download.py:75: resolve into the weights cache."""
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
