"""Legacy profiler facade (reference: python/paddle/utils/profiler.py:39
ProfilerOptions / :76 Profiler / get_profiler) — thin options-bag plus a
start/stop context delegating to the modern paddle.profiler engine."""
from __future__ import annotations

__all__ = ["ProfilerOptions", "Profiler", "get_profiler"]


class ProfilerOptions:
    _default = {
        "state": "All", "sorted_key": "default", "tracer_level": "Default",
        "batch_range": [0, 100], "output_thread_detail": False,
        "profile_path": "none", "timeline_path": "none",
        "op_summary_path": "none",
    }

    def __init__(self, options=None):
        import copy

        self.options = copy.deepcopy(self._default)  # batch_range is a
        # mutable list; a shallow copy would alias it across instances
        if options is not None:
            self.options.update(options)

    def with_state(self, state):
        new = ProfilerOptions(self.options)
        new.options["state"] = state
        return new

    def __getitem__(self, name):
        if name not in self.options:
            raise ValueError(f"ProfilerOptions does not have option {name}")
        return self.options[name]


class Profiler:
    def __init__(self, enabled=True, options=None):
        from ..profiler import Profiler as _Modern

        self._options = options if isinstance(options, ProfilerOptions) \
            else ProfilerOptions(options)
        self._enabled = enabled
        self._inner = _Modern() if enabled else None
        self._running = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()

    def start(self):
        if self._enabled and not self._running:
            self._inner.start()
            self._running = True

    def stop(self):
        if self._enabled and self._running:
            self._inner.stop()
            self._running = False

    def reset(self):
        if self._running:
            self.stop()
        if self._enabled:
            from ..profiler import Profiler as _Modern

            self._inner = _Modern()

    def record_step(self, change_profiler_status=True):
        if self._enabled and self._running:
            self._inner.step()


_profiler = None


def get_profiler():
    global _profiler
    if _profiler is None:
        _profiler = Profiler()
    return _profiler
