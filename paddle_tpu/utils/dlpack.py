"""DLPack interop (reference: python/paddle/utils/dlpack.py:26,62).

TPU-native: jax arrays already speak the DLPack protocol, so to_dlpack
hands out the underlying buffer's capsule (zero-copy on CPU; device
buffers export their device view) and from_dlpack accepts either a
capsule or any __dlpack__-capable producer (torch, numpy, cupy, jax).
"""
from __future__ import annotations

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor -> DLPack capsule (reference dlpack.py:26).

    A bare capsule carries no device tag, so the export is ALWAYS
    host-resident: device (TPU) buffers are copied to host first. The
    capsule consumers in scope (torch-cpu, numpy, a fresh jax array)
    are host-side; zero-copy device export goes through the array
    protocol (`jnp.from_dlpack(tensor._value)`), not the capsule."""
    import numpy as np

    from ..core.tensor import Tensor

    v = x._value if isinstance(x, Tensor) else x
    if getattr(getattr(v, "sharding", None), "device_set", None) and any(
            d.platform != "cpu" for d in v.sharding.device_set):
        v = np.asarray(v)  # device -> host copy
    return v.__dlpack__()


class _CapsuleProducer:
    """Adapter: a bare DLPack capsule (the reference's to_dlpack output)
    presented through the modern producer protocol jnp.from_dlpack
    expects. A capsule carries no device info, so it is presented as
    host-resident (kDLCPU) — which is what a capsule that crossed a
    framework boundary is."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, stream=None):
        return self._capsule

    def __dlpack_device__(self):
        return (1, 0)  # (kDLCPU, device 0)


def from_dlpack(dlpack):
    """DLPack capsule or __dlpack__-capable object -> Tensor
    (reference dlpack.py:62; also accepts producers directly, the
    modern protocol form torch/numpy/jax use)."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    if not hasattr(dlpack, "__dlpack__"):  # bare capsule
        dlpack = _CapsuleProducer(dlpack)
    return Tensor(jnp.from_dlpack(dlpack))
