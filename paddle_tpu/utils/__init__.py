"""paddle.utils (reference: python/paddle/utils/__init__.py)."""
from . import cpp_extension  # noqa: F401
from .custom_op import CustomOp, register_custom_op  # noqa: F401

__all__ = ["cpp_extension", "try_import", "register_custom_op", "CustomOp"]


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or f"please install {module_name}") from e
