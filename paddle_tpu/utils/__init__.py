"""paddle.utils (reference: python/paddle/utils/__init__.py)."""
from . import cpp_extension  # noqa: F401
from . import dlpack  # noqa: F401
from . import download  # noqa: F401
from . import image_util  # noqa: F401
from . import op_version  # noqa: F401
from . import unique_name  # noqa: F401
from .custom_op import CustomOp, register_custom_op  # noqa: F401
from .install_check import run_check  # noqa: F401
from .op_version import OpLastCheckpointChecker  # noqa: F401
from .profiler import Profiler, ProfilerOptions, get_profiler  # noqa: F401

try:  # reference re-exports a vendored gast; the real one is in the image
    import gast  # noqa: F401
except ImportError:  # pragma: no cover
    gast = None

__all__ = ["cpp_extension", "try_import", "register_custom_op", "CustomOp",
           "deprecated", "run_check", "require_version", "unique_name",
           "download", "dlpack", "op_version", "image_util"]


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or f"please install {module_name}") from e


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator marking an API deprecated (reference: utils/deprecated.py).
    level 0 = docstring note only, 1 = warn on call, 2 = raise."""
    import functools
    import warnings

    def wrap(fn):
        note = (f"Deprecated since {since or 'unknown'}. {reason} "
                f"{'Use ' + update_to + ' instead.' if update_to else ''}")
        if fn.__doc__:
            fn.__doc__ = note + "\n\n" + fn.__doc__
        else:
            fn.__doc__ = note

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            if level == 2:
                raise RuntimeError(f"{fn.__name__}: {note}")
            if level == 1:
                warnings.warn(note, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return inner
    return wrap


def require_version(min_version, max_version=None):
    """Check the installed framework version is inside [min, max]
    (reference: fluid/framework.require_version)."""
    from .. import __version__

    if not isinstance(min_version, str) or (
            max_version is not None and not isinstance(max_version, str)):
        raise TypeError("version bounds must be str")

    def key(v):
        return [int(x) for x in str(v).replace("-", ".").split(".")
                if x.isdigit()][:3]

    cur = key(__version__)
    if key(min_version) > cur:
        raise Exception(
            f"version {__version__} is older than required {min_version}")
    if max_version is not None and key(max_version) < cur:
        raise Exception(
            f"version {__version__} is newer than allowed {max_version}")
    return True


# run_check comes from install_check (dygraph + static smoke-train, the
# reference install_check.py:220 contract)
