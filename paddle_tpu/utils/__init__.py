"""paddle.utils (reference: python/paddle/utils/__init__.py)."""
from . import cpp_extension  # noqa: F401
from .custom_op import CustomOp, register_custom_op  # noqa: F401

__all__ = ["cpp_extension", "try_import", "register_custom_op", "CustomOp"]


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or f"please install {module_name}") from e


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator marking an API deprecated (reference: utils/deprecated.py).
    level 0 = docstring note only, 1 = warn on call, 2 = raise."""
    import functools
    import warnings

    def wrap(fn):
        note = (f"Deprecated since {since or 'unknown'}. {reason} "
                f"{'Use ' + update_to + ' instead.' if update_to else ''}")
        if fn.__doc__:
            fn.__doc__ = note + "\n\n" + fn.__doc__
        else:
            fn.__doc__ = note

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            if level == 2:
                raise RuntimeError(f"{fn.__name__}: {note}")
            if level == 1:
                warnings.warn(note, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return inner
    return wrap


def require_version(min_version, max_version=None):
    """Check the installed framework version is inside [min, max]."""
    from .. import __version__

    def key(v):
        return [int(x) for x in str(v).replace("-", ".").split(".")
                if x.isdigit()][:3]

    cur = key(__version__)
    if key(min_version) > cur:
        raise Exception(
            f"version {__version__} is older than required {min_version}")
    if max_version is not None and key(max_version) < cur:
        raise Exception(
            f"version {__version__} is newer than allowed {max_version}")
    return True


def run_check():
    """Smoke-test the install: run one fused matmul on the attached device
    (reference utils/install_check.py trains a tiny net)."""
    import jax
    import jax.numpy as jnp
    x = jnp.ones((8, 8), jnp.float32)
    y = jax.jit(lambda a: (a @ a).sum())(x)
    assert float(y) == 8.0 * 8.0 * 8.0
    plat = jax.devices()[0].platform
    print(f"PaddleTPU works well on 1 {plat} device.")
    return True


__all__ += ["deprecated", "require_version", "run_check"]
