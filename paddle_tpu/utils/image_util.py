"""Legacy image helpers (reference: python/paddle/utils/image_util.py —
PIL/numpy preprocessing used by the v1-era tutorials).

numpy-only re-implementation (bilinear resize via index interpolation);
decode_jpeg gates on Pillow if a real JPEG byte-string arrives.
"""
from __future__ import annotations

import numpy as np

__all__ = []


def resize_image(img, target_size):
    """[C, H, W] (or [H, W]) -> shorter side == target_size, bilinear."""
    arr = np.asarray(img)
    chw = arr.ndim == 3
    if chw:
        c, h, w = arr.shape
    else:
        h, w = arr.shape
    scale = target_size / min(h, w)
    nh, nw = max(1, int(round(h * scale))), max(1, int(round(w * scale)))
    ys = np.clip(np.linspace(0, h - 1, nh), 0, h - 1)
    xs = np.clip(np.linspace(0, w - 1, nw), 0, w - 1)
    y0, x0 = np.floor(ys).astype(int), np.floor(xs).astype(int)
    y1, x1 = np.minimum(y0 + 1, h - 1), np.minimum(x0 + 1, w - 1)
    wy, wx = (ys - y0)[:, None], (xs - x0)[None, :]

    def _interp(a):
        top = a[y0][:, x0] * (1 - wx) + a[y0][:, x1] * wx
        bot = a[y1][:, x0] * (1 - wx) + a[y1][:, x1] * wx
        return top * (1 - wy) + bot * wy

    if chw:
        return np.stack([_interp(arr[i]) for i in range(c)])
    return _interp(arr)


def flip(im):
    """Horizontal flip, [C, H, W] or [H, W] (reference image_util.py:35)."""
    im = np.asarray(im)
    return im[:, :, ::-1] if im.ndim == 3 else im[:, ::-1]


def crop_img(im, inner_size, color=True, test=True):
    """Center (test) or random crop to inner_size square
    (reference image_util.py:47)."""
    im = np.asarray(im)
    if color and im.ndim == 3:
        _, h, w = im.shape
    else:
        h, w = im.shape[-2:]
    if test:
        top, left = (h - inner_size) // 2, (w - inner_size) // 2
    else:
        top = np.random.randint(0, max(1, h - inner_size + 1))
        left = np.random.randint(0, max(1, w - inner_size + 1))
    sl = (slice(top, top + inner_size), slice(left, left + inner_size))
    out = im[(slice(None),) + sl] if im.ndim == 3 else im[sl]
    if not test and np.random.randint(2):
        out = flip(out)  # reference: train mode random-flips the crop
    return out


def decode_jpeg(jpeg_string):
    """JPEG bytes -> [C, H, W] float array (needs Pillow)."""
    import io

    try:
        from PIL import Image
    except ImportError as e:  # loud gate: no image codec in this image
        raise ImportError(
            "decode_jpeg needs Pillow, which is not installed in this "
            "deployment; decode outside or install Pillow") from e
    img = np.asarray(Image.open(io.BytesIO(jpeg_string)).convert("RGB"))
    return img.transpose(2, 0, 1).astype(np.float32)


def preprocess_img(im, img_mean, crop_size, is_train, color=True):
    """resize->crop->mean-subtract pipeline (reference image_util.py:98)."""
    im = crop_img(np.asarray(im, dtype=np.float32), crop_size, color,
                  test=not is_train)
    mean = np.asarray(img_mean, dtype=np.float32).reshape(im.shape)
    return (im - mean).flatten()


def load_meta(meta_path, mean_img_size, crop_size, color=True):
    """Load a pickled mean image and center-crop it to crop_size."""
    import pickle

    with open(meta_path, "rb") as f:
        mean = pickle.load(f, encoding="latin1")["mean"]
    border = (mean_img_size - crop_size) // 2
    if color:
        mean = mean.reshape(3, mean_img_size, mean_img_size)
        return mean[:, border:border + crop_size,
                    border:border + crop_size].flatten()
    mean = mean.reshape(mean_img_size, mean_img_size)
    return mean[border:border + crop_size,
                border:border + crop_size].flatten()
