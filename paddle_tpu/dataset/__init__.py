"""paddle.dataset — legacy reader-style dataset modules.

Reference: python/paddle/dataset/{mnist,cifar,imdb,imikolov,uci_housing,
movielens,wmt14,wmt16,conll05,flowers,voc2012}.py — each exposes
train()/test() creator functions returning sample generators.

TPU build: thin reader adapters over the map-style datasets in
paddle.vision.datasets / paddle.text (which parse the reference file
formats); `common` keeps the md5/download helper signatures with download
disabled (zero-egress image).
"""
from __future__ import annotations

import sys
import types

__all__ = ["mnist", "cifar", "imdb", "imikolov", "uci_housing", "movielens",
           "wmt14", "wmt16", "conll05", "flowers", "voc2012", "common",
           "image"]


def _reader_of(dataset_factory):
    def reader_creator(*args, **kwargs):
        def reader():
            ds = dataset_factory(*args, **kwargs)
            for i in range(len(ds)):
                item = ds[i]
                yield tuple(item) if isinstance(item, (tuple, list)) \
                    else (item,)

        return reader

    return reader_creator


def _module(name, **attrs):
    mod = types.ModuleType(f"{__name__}.{name}")
    for k, v in attrs.items():
        setattr(mod, k, v)
    sys.modules[mod.__name__] = mod
    return mod


def _vision(name):
    from .. import vision

    return getattr(vision.datasets, name)


def _cycled(reader_creator, cycle):
    if not cycle:
        return reader_creator()

    base = reader_creator()

    def forever():
        while True:
            yield from base()

    return forever


def _check_word_idx(word_idx, internal):
    """The class datasets own their dictionaries; a DIFFERENT external
    dict cannot be honored — fail loudly rather than silently encoding
    with other ids (legacy reference readers encoded with the caller's
    dict)."""
    if word_idx is not None and word_idx != internal:
        raise NotImplementedError(
            "paddle.dataset shims encode with the dataset's own word "
            "dict; pass word_idx=None (or the dict returned by "
            "word_dict()/build_dict())")


def _mnist_train():
    return _reader_of(lambda: _vision("MNIST")(mode="train"))()


def _mnist_test():
    return _reader_of(lambda: _vision("MNIST")(mode="test"))()


mnist = _module("mnist", train=lambda: _mnist_train(),
                test=lambda: _mnist_test())

cifar = _module(
    "cifar",
    train10=lambda cycle=False: _cycled(_reader_of(
        lambda: _vision("Cifar10")(mode="train")), cycle),
    test10=lambda cycle=False: _cycled(_reader_of(
        lambda: _vision("Cifar10")(mode="test")), cycle),
    train100=lambda: _reader_of(
        lambda: _vision("Cifar100")(mode="train"))(),
    test100=lambda: _reader_of(
        lambda: _vision("Cifar100")(mode="test"))(),
)


def _text(name):
    from .. import text

    return getattr(text, name)


def _imdb_reader(mode, word_idx):
    ds = _text("Imdb")(mode=mode)
    _check_word_idx(word_idx, ds.word_idx)

    def reader():
        for i in range(len(ds)):
            yield tuple(ds[i])

    return reader


imdb = _module(
    "imdb",
    train=lambda word_idx=None: _imdb_reader("train", word_idx),
    test=lambda word_idx=None: _imdb_reader("test", word_idx),
    word_dict=lambda: _text("Imdb")(mode="train").word_idx,
)


def _imikolov_reader(mode, word_idx, n):
    ds = _text("Imikolov")(data_type="NGRAM", window_size=n, mode=mode,
                           min_word_freq=0)
    _check_word_idx(word_idx, ds.word_idx)

    def reader():
        for i in range(len(ds)):
            yield tuple(ds[i])

    return reader


imikolov = _module(
    "imikolov",
    train=lambda word_idx=None, n=5: _imikolov_reader("train", word_idx, n),
    test=lambda word_idx=None, n=5: _imikolov_reader("test", word_idx, n),
    build_dict=lambda min_word_freq=50: _text("Imikolov")(
        data_type="NGRAM", window_size=5,
        min_word_freq=min_word_freq).word_idx,
)

uci_housing = _module(
    "uci_housing",
    train=lambda: _reader_of(
        lambda: _text("UCIHousing")(mode="train"))(),
    test=lambda: _reader_of(
        lambda: _text("UCIHousing")(mode="test"))(),
    feature_range=lambda maximums, minimums: None,
)

movielens = _module(
    "movielens",
    train=lambda: _reader_of(
        lambda: _text("Movielens")(mode="train"))(),
    test=lambda: _reader_of(
        lambda: _text("Movielens")(mode="test"))(),
    max_movie_id=lambda: max(
        _text("Movielens")(mode="train").movie_info),
    max_user_id=lambda: max(
        _text("Movielens")(mode="train").user_info),
)

wmt14 = _module(
    "wmt14",
    train=lambda dict_size=-1: _reader_of(
        lambda: _text("WMT14")(mode="train", dict_size=dict_size))(),
    test=lambda dict_size=-1: _reader_of(
        lambda: _text("WMT14")(mode="test", dict_size=dict_size))(),
)

wmt16 = _module(
    "wmt16",
    train=lambda src_dict_size=-1, trg_dict_size=-1, src_lang="en":
        _reader_of(lambda: _text("WMT16")(
            mode="train", src_dict_size=src_dict_size,
            trg_dict_size=trg_dict_size, lang=src_lang))(),
    test=lambda src_dict_size=-1, trg_dict_size=-1, src_lang="en":
        _reader_of(lambda: _text("WMT16")(
            mode="test", src_dict_size=src_dict_size,
            trg_dict_size=trg_dict_size, lang=src_lang))(),
)

conll05 = _module(
    "conll05",
    test=lambda: _reader_of(lambda: _text("Conll05st")())(),
    get_dict=lambda: _text("Conll05st")().get_dict(),
    get_embedding=lambda: _text("Conll05st")().get_embedding(),
)

flowers = _module(
    "flowers",
    train=lambda: _reader_of(
        lambda: _vision("Flowers")(mode="train"))(),
    test=lambda: _reader_of(
        lambda: _vision("Flowers")(mode="test"))(),
    valid=lambda: _reader_of(
        lambda: _vision("Flowers")(mode="valid"))(),
)

voc2012 = _module(
    "voc2012",
    train=lambda: _reader_of(
        lambda: _vision("VOC2012")(mode="train"))(),
    test=lambda: _reader_of(
        lambda: _vision("VOC2012")(mode="test"))(),
    val=lambda: _reader_of(
        lambda: _vision("VOC2012")(mode="valid"))(),
)


def _md5file(fname):
    import hashlib

    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            h.update(chunk)
    return h.hexdigest()


def _download(url, module_name, md5sum, save_name=None):
    raise RuntimeError(
        "paddle.dataset downloads need network access, which this build "
        "does not have; pass local data files to the paddle.text / "
        "paddle.vision dataset classes instead")


common = _module("common", md5file=_md5file, download=_download,
                 DATA_HOME="/tmp/paddle_tpu_data")

image = _module("image")
