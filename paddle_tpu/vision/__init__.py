"""paddle.vision (reference: python/paddle/vision/__init__.py)."""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401
from .models import *  # noqa: F401,F403
from .image import get_image_backend, image_load, set_image_backend  # noqa: F401
