"""Vision datasets (reference: python/paddle/vision/datasets/*).

Zero-egress environment: when the on-disk dataset is absent, each dataset
falls back to a deterministic synthetic sample set with the real shapes and
label spaces (mode='synthetic'), so training/eval pipelines run unchanged.
"""
from __future__ import annotations

import os

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers", "VOC2012",
           "DatasetFolder", "ImageFolder"]


class _SyntheticImageDataset(Dataset):
    """Deterministic fake images: content seeded by index, labels derived
    from content so models can actually fit the data."""

    IMG_SHAPE = (1, 28, 28)
    N_CLASSES = 10
    N = 1024

    def __init__(self, mode="train", transform=None, backend=None):
        self.mode = mode
        self.transform = transform
        self.backend = backend
        seed = {"train": 0, "test": 10_000, "valid": 20_000}.get(mode, 0)
        self._seed = seed

    def __len__(self):
        return self.N if self.mode == "train" else self.N // 4

    def _raw(self, idx):
        rng = np.random.RandomState(self._seed + idx)
        c, h, w = self.IMG_SHAPE
        label = idx % self.N_CLASSES
        img = rng.rand(c, h, w).astype(np.float32) * 0.3
        # class-dependent pattern: bright band at row block `label`
        band = h // self.N_CLASSES
        img[:, label * band:(label + 1) * band, :] += 0.7
        return img, label

    def __getitem__(self, idx):
        img, label = self._raw(idx)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)


class MNIST(_SyntheticImageDataset):
    """reference: python/paddle/vision/datasets/mnist.py. Reads IDX files
    when image_path/label_path exist; synthetic fallback otherwise."""

    IMG_SHAPE = (1, 28, 28)
    N_CLASSES = 10

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        super().__init__(mode, transform, backend)
        self._images = self._labels = None
        if image_path and label_path and os.path.exists(image_path) and \
                os.path.exists(label_path):
            self._images, self._labels = _read_idx(image_path, label_path)

    def __len__(self):
        if self._images is not None:
            return len(self._images)
        return super().__len__()

    def __getitem__(self, idx):
        if self._images is not None:
            img = self._images[idx].astype(np.float32)[None] / 255.0
            label = np.asarray(self._labels[idx], np.int64)
            if self.transform is not None:
                img = self.transform(img)
            return img, label
        return super().__getitem__(idx)


class FashionMNIST(MNIST):
    pass


def _read_idx(image_path, label_path):
    import gzip
    import struct

    op = gzip.open if image_path.endswith(".gz") else open
    with op(image_path, "rb") as f:
        _, n, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
    op = gzip.open if label_path.endswith(".gz") else open
    with op(label_path, "rb") as f:
        struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), np.uint8)
    return images, labels


class Cifar10(_SyntheticImageDataset):
    IMG_SHAPE = (3, 32, 32)
    N_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        super().__init__(mode, transform, backend)


class Cifar100(Cifar10):
    N_CLASSES = 100


class Flowers(_SyntheticImageDataset):
    IMG_SHAPE = (3, 64, 64)
    N_CLASSES = 102

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        super().__init__(mode, transform, backend)


class VOC2012(_SyntheticImageDataset):
    """Segmentation pairs: (image, mask)."""

    IMG_SHAPE = (3, 64, 64)
    N_CLASSES = 21

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        super().__init__(mode, transform, backend)

    def __getitem__(self, idx):
        img, label = self._raw(idx)
        rng = np.random.RandomState(self._seed + idx + 1)
        mask = rng.randint(0, self.N_CLASSES,
                           self.IMG_SHAPE[1:]).astype(np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, mask


class DatasetFolder(Dataset):
    """reference: python/paddle/vision/datasets/folder.py."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        extensions = extensions or (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            d = os.path.join(root, c)
            for fn in sorted(os.listdir(d)):
                if is_valid_file is not None:
                    ok = is_valid_file(fn)
                else:
                    ok = fn.lower().endswith(extensions)
                if ok:
                    self.samples.append((os.path.join(d, fn),
                                         self.class_to_idx[c]))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)


class ImageFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        extensions = extensions or (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                if is_valid_file is not None:
                    ok = is_valid_file(fn)
                else:
                    ok = fn.lower().endswith(extensions)
                if ok:
                    self.samples.append(os.path.join(dirpath, fn))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image

        with Image.open(path) as im:
            return np.asarray(im.convert("RGB")).transpose(2, 0, 1) \
                .astype(np.float32) / 255.0
    except ImportError:
        raise RuntimeError(
            "PIL unavailable; use .npy images or pass a custom loader")
