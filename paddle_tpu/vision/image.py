"""Image IO backend switch (reference: python/paddle/vision/image.py:23).

Backends: 'pil' (default) and 'cv2'. Decoding runs on host CPU; arrays are
staged to HBM by the DataLoader, so the backend choice only affects host
decode throughput.
"""
from __future__ import annotations

__all__ = ["set_image_backend", "get_image_backend", "image_load"]

_image_backend = "pil"


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2', 'tensor'], "
            f"but got {backend}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image as PIL.Image ('pil') or np.ndarray HWC-BGR ('cv2')."""
    backend = backend or _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2', 'tensor'], "
            f"but got {backend}")
    if backend == "cv2":
        import numpy as np
        try:
            import cv2
            return cv2.imread(path)
        except ImportError:
            from PIL import Image
            return np.asarray(Image.open(path))[..., ::-1].copy()
    from PIL import Image
    img = Image.open(path)
    if backend == "tensor":
        import numpy as np
        from ..core.tensor import Tensor
        return Tensor(np.asarray(img))
    return img
