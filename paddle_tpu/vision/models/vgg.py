"""VGG + AlexNet (reference: python/paddle/vision/models/vgg.py, alexnet.py)."""
from __future__ import annotations

from ... import nn

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19", "AlexNet", "alexnet"]

_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512,
          "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512,
          512, "M", 512, 512, 512, 512, "M"],
}


def _make_layers(cfg, batch_norm=False):
    layers = []
    in_c = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers.append(nn.Conv2D(in_c, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            in_c = v
    return nn.Sequential(*layers)


class VGG(nn.Layer):
    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ... import tensor as T

            x = T.flatten(x, 1)
            x = self.classifier(x)
        return x


def _vgg(cfg, batch_norm, pretrained, **kwargs):
    model = VGG(_make_layers(_CFGS[cfg], batch_norm), **kwargs)
    if pretrained:
        if batch_norm:
            raise NotImplementedError(
                "no published weights for the batch_norm VGG variants")
        from ._pretrained import load_pretrained

        arch = {"A": "vgg11", "B": "vgg13", "D": "vgg16",
                "E": "vgg19"}[cfg]
        load_pretrained(model, arch)
    return model


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("A", batch_norm, pretrained, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("B", batch_norm, pretrained, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("D", batch_norm, pretrained, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("E", batch_norm, pretrained, **kwargs)


class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
                nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            from ... import tensor as T

            x = self.classifier(T.flatten(x, 1))
        return x


def alexnet(pretrained=False, **kwargs):
    model = AlexNet(**kwargs)
    if pretrained:
        from ._pretrained import load_pretrained

        load_pretrained(model, "alexnet")
    return model
