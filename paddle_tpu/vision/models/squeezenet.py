"""SqueezeNet + ShuffleNetV2 (reference: python/paddle/vision/models/
squeezenet.py, shufflenetv2.py)."""
from __future__ import annotations

from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1", "ShuffleNetV2",
           "shufflenet_v2_x0_25", "shufflenet_v2_x0_33", "shufflenet_v2_x0_5",
           "shufflenet_v2_x1_0", "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
           "shufflenet_v2_swish"]


class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze_c, e1_c, e3_c):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze_c, 1)
        self.expand1 = nn.Conv2D(squeeze_c, e1_c, 1)
        self.expand3 = nn.Conv2D(squeeze_c, e3_c, 3, padding=1)

    def forward(self, x):
        from ... import tensor as T

        s = nn.functional.relu(self.squeeze(x))
        return T.concat([nn.functional.relu(self.expand1(s)),
                         nn.functional.relu(self.expand3(s))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
                nn.AdaptiveAvgPool2D(1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            from ... import tensor as T

            x = self.classifier(x)
            x = T.flatten(x, 1)
        return x


def squeezenet1_0(pretrained=False, **kwargs):
    if pretrained:
        from ._pretrained import load_pretrained

        return load_pretrained(SqueezeNet("1.0", **kwargs),
                               "squeezenet1_0")
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    if pretrained:
        from ._pretrained import load_pretrained

        return load_pretrained(SqueezeNet("1.1", **kwargs),
                               "squeezenet1_1")
    return SqueezeNet("1.1", **kwargs)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        if stride == 2:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=2, padding=1, groups=in_c,
                          bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), act_layer())
            b2_in = in_c
        else:
            self.branch1 = None
            b2_in = in_c // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act_layer(),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act_layer())

    def forward(self, x):
        from ... import tensor as T

        if self.stride == 1:
            x1, x2 = T.split(x, 2, axis=1)
            out = T.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = T.concat([self.branch1(x), self.branch2(x)], axis=1)
        return nn.functional.channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stage_repeats = [4, 8, 4]
        channels = {0.25: [24, 24, 48, 96, 512],
                    0.33: [24, 32, 64, 128, 512],
                    0.5: [24, 48, 96, 192, 1024],
                    1.0: [24, 116, 232, 464, 1024],
                    1.5: [24, 176, 352, 704, 1024],
                    2.0: [24, 244, 488, 976, 2048]}[scale]
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, channels[0], 3, stride=2, padding=1,
                      bias_attr=False),
            nn.BatchNorm2D(channels[0]), act_layer())
        self.maxpool = nn.MaxPool2D(3, 2, 1)
        stages = []
        in_c = channels[0]
        for i, reps in enumerate(stage_repeats):
            out_c = channels[i + 1]
            units = [_ShuffleUnit(in_c, out_c, 2, act)]
            for _ in range(reps - 1):
                units.append(_ShuffleUnit(out_c, out_c, 1, act))
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_c, channels[-1], 1, bias_attr=False),
            nn.BatchNorm2D(channels[-1]), act_layer())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(channels[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.stages(x)
        x = self.conv_last(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ... import tensor as T

            x = self.fc(T.flatten(x, 1))
        return x


def _shufflenet(scale, act="relu", pretrained=False, **kwargs):
    model = ShuffleNetV2(scale=scale, act=act, **kwargs)
    if pretrained:
        from ._pretrained import load_pretrained

        arch = ("shufflenet_v2_swish" if act == "swish" else
                "shufflenet_v2_x" + {0.25: "0_25", 0.33: "0_33",
                                     0.5: "0_5", 1.0: "1_0",
                                     1.5: "1_5", 2.0: "2_0"}[scale])
        load_pretrained(model, arch)
    return model


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, act="swish", pretrained=pretrained, **kwargs)
