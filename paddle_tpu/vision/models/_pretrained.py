"""Pretrained-weight plumbing for the vision model zoo (reference:
python/paddle/vision/models/*.py model_urls + hapi download).

Zero-egress deployment: `pretrained=True` resolves the OFFICIAL weight
URL against the local cache (~/.cache/paddle/hapi/weights) via
utils.download — a pre-placed or file://-sideloaded .pdparams loads
exactly like the reference; a cache miss raises the loud zero-egress
error naming the path to pre-place, which beats the old flat
NotImplementedError because it makes sideloading actually work.
"""
from __future__ import annotations

__all__ = ["load_pretrained", "WEIGHT_URLS"]

# (url, md5) pairs exactly as published by the reference model zoo
# (reference vision/models/{resnet,vgg,mobilenetv1,mobilenetv2,densenet,
# resnext,squeezenet}.py model_urls)
_HAPI = "https://paddle-hapi.bj.bcebos.com/models/"
_IMN = ("https://paddle-imagenet-models-name.bj.bcebos.com/dygraph/")
WEIGHT_URLS = {
    "resnet18": (_HAPI + "resnet18.pdparams",
                 "cf548f46534aa3560945be4b95cd11c4"),
    "resnet34": (_HAPI + "resnet34.pdparams",
                 "8d2275cf8706028345f78ac0e1d31969"),
    "resnet50": (_HAPI + "resnet50.pdparams",
                 "ca6f485ee1ab0492d38f323885b0ad80"),
    "resnet101": (_HAPI + "resnet101.pdparams",
                  "02f35f034ca3858e1e54d4036443c92d"),
    "resnet152": (_HAPI + "resnet152.pdparams",
                  "7ad16a2f1e7333859ff986138630fd7a"),
    "wide_resnet50_2": (_HAPI + "wide_resnet50_2.pdparams",
                        "0282f804d73debdab289bd9fea3fa6dc"),
    "wide_resnet101_2": (_HAPI + "wide_resnet101_2.pdparams",
                         "d4360a2d23657f059216f5d5a1a9ac93"),
    "vgg16": (_HAPI + "vgg16.pdparams",
              "89bbffc0f87d260be9b8cdc169c991c4"),
    "vgg19": (_HAPI + "vgg19.pdparams",
              "23b18bb13d8894f60f54e642be79a0dd"),
    "mobilenetv1_1.0": (_HAPI + "mobilenet_v1_x1.0.pdparams",
                        "42a154c2f26f86e7457d6daded114e8c"),
    "mobilenetv2_1.0": (_HAPI + "mobilenet_v2_x1.0.pdparams",
                        "0340af0a901346c8d46f4529882fb63d"),
    "densenet121": (_IMN + "DenseNet121_pretrained.pdparams",
                    "db1b239ed80a905290fd8b01d3af08e4"),
    "densenet161": (_IMN + "DenseNet161_pretrained.pdparams",
                    "62158869cb315098bd25ddbfd308a853"),
    "densenet169": (_IMN + "DenseNet169_pretrained.pdparams",
                    "82cc7c635c3f19098c748850efb2d796"),
    "densenet201": (_IMN + "DenseNet201_pretrained.pdparams",
                    "16ca29565a7712329cf9e36e02caaf58"),
    "densenet264": (_IMN + "DenseNet264_pretrained.pdparams",
                    "3270ce516b85370bba88cfdd9f60bff4"),
    "resnext50_32x4d": (_IMN + "ResNeXt50_32x4d_pretrained.pdparams",
                        "bf04add2f7fd22efcbe91511bcd1eebe"),
    "resnext50_64x4d": (_IMN + "ResNeXt50_64x4d_pretrained.pdparams",
                        "46307df0e2d6d41d3b1c1d22b00abc69"),
    "resnext101_32x4d": (_IMN + "ResNeXt101_32x4d_pretrained.pdparams",
                         "078ca145b3bea964ba0544303a43c36d"),
    "resnext101_64x4d": (_IMN + "ResNeXt101_64x4d_pretrained.pdparams",
                         "4edc0eb32d3cc5d80eff7cab32cd5c64"),
    "resnext152_32x4d": (_IMN + "ResNeXt152_32x4d_pretrained.pdparams",
                         "7971cc994d459af167c502366f866378"),
    "resnext152_64x4d": (_IMN + "ResNeXt152_64x4d_pretrained.pdparams",
                         "836943f03709efec364d486c57d132de"),
    "squeezenet1_0": (_IMN + "SqueezeNet1_0_pretrained.pdparams",
                      "30b95af60a2178f03cf9b66cd77e1db1"),
    "squeezenet1_1": (_IMN + "SqueezeNet1_1_pretrained.pdparams",
                      "a11250d3a1f91d7131fd095ebbf09eee"),
    "googlenet": (_IMN + "GoogLeNet_pretrained.pdparams",
                  "80c06f038e905c53ab32c40eca6e26ae"),
    "inception_v3": (_IMN + "legendary_models/"
                     "InceptionV3_pretrained.pdparams",
                     "e4d0905a818f6bb7946e881777a8a935"),
    "alexnet": (_IMN + "AlexNet_pretrained.pdparams",
                "7f0f9f737132e02732d75a1459d98a43"),
    "shufflenet_v2_x0_25": (_IMN + "ShuffleNetV2_x0_25_pretrained"
                            ".pdparams",
                            "e753404cbd95027759c5f56ecd6c9c4b"),
    "shufflenet_v2_x0_33": (_IMN + "ShuffleNetV2_x0_33_pretrained"
                            ".pdparams",
                            "776e3cf9a4923abdfce789c45b8fe1f2"),
    "shufflenet_v2_x0_5": (_IMN + "ShuffleNetV2_x0_5_pretrained"
                           ".pdparams",
                           "e3649cf531566917e2969487d2bc6b60"),
    "shufflenet_v2_x1_0": (_IMN + "ShuffleNetV2_x1_0_pretrained"
                           ".pdparams",
                           "7821c348ea34e58847c43a08a4ac0bdf"),
    "shufflenet_v2_x1_5": (_IMN + "ShuffleNetV2_x1_5_pretrained"
                           ".pdparams",
                           "93a07fa557ab2d8803550f39e5b6c391"),
    "shufflenet_v2_x2_0": (_IMN + "ShuffleNetV2_x2_0_pretrained"
                           ".pdparams",
                           "4ab1f622fd0d341e0f84b4e057797563"),
    "shufflenet_v2_swish": (_IMN + "ShuffleNetV2_swish_pretrained"
                            ".pdparams",
                            "daff38b3df1b3748fccbb13cfdf02519"),
}


def load_pretrained(model, arch):
    """Resolve arch's official weights through the local cache and load
    them into `model` (md5-checked)."""
    if arch not in WEIGHT_URLS:
        raise NotImplementedError(
            f"no published weights for '{arch}'; load a state_dict with "
            "model.set_state_dict instead")
    url, md5 = WEIGHT_URLS[arch]
    from ...framework.io import load
    from ...utils.download import get_weights_path_from_url

    path = get_weights_path_from_url(url, md5)
    result = model.set_state_dict(load(path))
    if isinstance(result, tuple):
        missing, unexpected = result
        if missing or unexpected:
            # a silently-partial load would claim "pretrained" on random
            # init; refuse with the key diff
            raise ValueError(
                f"pretrained weights for '{arch}' do not match the "
                f"model: {len(missing)} missing keys "
                f"(e.g. {missing[:3]}), {len(unexpected)} unexpected "
                f"(e.g. {unexpected[:3]})")
    return model
