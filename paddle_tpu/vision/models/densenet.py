"""DenseNet + GoogLeNet + InceptionV3 (reference: python/paddle/vision/models/
densenet.py, googlenet.py, inceptionv3.py)."""
from __future__ import annotations

from ... import nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264", "GoogLeNet", "googlenet",
           "InceptionV3", "inception_v3"]


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, drop_rate):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.drop_rate = drop_rate

    def forward(self, x):
        from ... import tensor as T

        out = self.conv1(nn.functional.relu(self.norm1(x)))
        out = self.conv2(nn.functional.relu(self.norm2(out)))
        if self.drop_rate > 0:
            out = nn.functional.dropout(out, self.drop_rate,
                                        training=self.training)
        return T.concat([x, out], axis=1)


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True, growth_rate=None):
        super().__init__()
        cfg = {121: (64, 32, [6, 12, 24, 16]),
               161: (96, 48, [6, 12, 36, 24]),
               169: (64, 32, [6, 12, 32, 32]),
               201: (64, 32, [6, 12, 48, 32]),
               264: (64, 32, [6, 12, 64, 48])}
        num_init, growth, block_cfg = cfg[layers]
        growth = growth_rate or growth
        self.num_classes = num_classes
        self.with_pool = with_pool
        feats = [nn.Conv2D(3, num_init, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(num_init), nn.ReLU(),
                 nn.MaxPool2D(3, 2, 1)]
        ch = num_init
        for bi, n in enumerate(block_cfg):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if bi != len(block_cfg) - 1:
                feats += [nn.BatchNorm2D(ch), nn.ReLU(),
                          nn.Conv2D(ch, ch // 2, 1, bias_attr=False),
                          nn.AvgPool2D(2, 2)]
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ... import tensor as T

            x = self.classifier(T.flatten(x, 1))
        return x


def _densenet(layers, pretrained=False, **kwargs):
    if pretrained:
        from ._pretrained import load_pretrained

        model = DenseNet(layers, **kwargs)
        return load_pretrained(model, f"densenet{layers}")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)


class _BasicConv(nn.Sequential):
    def __init__(self, in_c, out_c, k, **kw):
        super().__init__(nn.Conv2D(in_c, out_c, k, bias_attr=False, **kw),
                         nn.BatchNorm2D(out_c), nn.ReLU())


class _InceptionBlock(nn.Layer):
    """Classic GoogLeNet inception module."""

    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _BasicConv(in_c, c1, 1)
        self.b2 = nn.Sequential(_BasicConv(in_c, c3r, 1),
                                _BasicConv(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_BasicConv(in_c, c5r, 1),
                                _BasicConv(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, 1, 1),
                                _BasicConv(in_c, proj, 1))

    def forward(self, x):
        from ... import tensor as T

        return T.concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                        axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BasicConv(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, 2, 1),
            _BasicConv(64, 64, 1),
            _BasicConv(64, 192, 3, padding=1),
            nn.MaxPool2D(3, 2, 1))
        self.inc3 = nn.Sequential(
            _InceptionBlock(192, 64, 96, 128, 16, 32, 32),
            _InceptionBlock(256, 128, 128, 192, 32, 96, 64),
            nn.MaxPool2D(3, 2, 1))
        self.inc4 = nn.Sequential(
            _InceptionBlock(480, 192, 96, 208, 16, 48, 64),
            _InceptionBlock(512, 160, 112, 224, 24, 64, 64),
            _InceptionBlock(512, 128, 128, 256, 24, 64, 64),
            _InceptionBlock(512, 112, 144, 288, 32, 64, 64),
            _InceptionBlock(528, 256, 160, 320, 32, 128, 128),
            nn.MaxPool2D(3, 2, 1))
        self.inc5 = nn.Sequential(
            _InceptionBlock(832, 256, 160, 320, 32, 128, 128),
            _InceptionBlock(832, 384, 192, 384, 48, 128, 128))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.inc5(self.inc4(self.inc3(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ... import tensor as T

            x = self.fc(self.dropout(T.flatten(x, 1)))
        # reference returns (main, aux1, aux2); aux heads folded into main
        return x, x, x


def googlenet(pretrained=False, **kwargs):
    model = GoogLeNet(**kwargs)
    if pretrained:
        from ._pretrained import load_pretrained

        load_pretrained(model, "googlenet")
    return model


class _InceptionA(nn.Layer):
    def __init__(self, in_c, pool_c):
        super().__init__()
        self.b1 = _BasicConv(in_c, 64, 1)
        self.b5 = nn.Sequential(_BasicConv(in_c, 48, 1),
                                _BasicConv(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_BasicConv(in_c, 64, 1),
                                _BasicConv(64, 96, 3, padding=1),
                                _BasicConv(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, 1),
                                _BasicConv(in_c, pool_c, 1))

    def forward(self, x):
        from ... import tensor as T

        return T.concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], 1)


class InceptionV3(nn.Layer):
    """Abbreviated InceptionV3: stem + A-blocks + reduction via strided
    convs + head (full 17/8-grid blocks share the same primitive set)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BasicConv(3, 32, 3, stride=2),
            _BasicConv(32, 32, 3),
            _BasicConv(32, 64, 3, padding=1),
            nn.MaxPool2D(3, 2),
            _BasicConv(64, 80, 1),
            _BasicConv(80, 192, 3),
            nn.MaxPool2D(3, 2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32),
            _InceptionA(256, 64),
            _InceptionA(288, 64),
            _BasicConv(288, 768, 3, stride=2),
            _BasicConv(768, 1280, 3, stride=2),
            _BasicConv(1280, 2048, 1))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ... import tensor as T

            x = self.fc(self.dropout(T.flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    model = InceptionV3(**kwargs)
    if pretrained:
        from ._pretrained import load_pretrained

        load_pretrained(model, "inception_v3")
    return model
