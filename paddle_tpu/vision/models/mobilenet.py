"""MobileNet V1/V2/V3 (reference: python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py, mobilenetv3.py)."""
from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV1", "mobilenet_v1", "MobileNetV2", "mobilenet_v2",
           "MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, k=3, stride=1, groups=1,
                 act=nn.ReLU):
        pad = (k - 1) // 2
        layers = [nn.Conv2D(in_c, out_c, k, stride, pad, groups=groups,
                            bias_attr=False), nn.BatchNorm2D(out_c)]
        if act is not None:
            layers.append(act())
        super().__init__(*layers)


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(int(ch * scale), 8)

        def dw_sep(in_c, out_c, stride):
            return nn.Sequential(
                _ConvBNReLU(in_c, in_c, 3, stride, groups=in_c),
                _ConvBNReLU(in_c, out_c, 1))

        self.features = nn.Sequential(
            _ConvBNReLU(3, c(32), 3, 2),
            dw_sep(c(32), c(64), 1),
            dw_sep(c(64), c(128), 2), dw_sep(c(128), c(128), 1),
            dw_sep(c(128), c(256), 2), dw_sep(c(256), c(256), 1),
            dw_sep(c(256), c(512), 2),
            *[dw_sep(c(512), c(512), 1) for _ in range(5)],
            dw_sep(c(512), c(1024), 2), dw_sep(c(1024), c(1024), 1))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ... import tensor as T

            x = self.fc(T.flatten(x, 1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(inp, hidden, 1, act=nn.ReLU6))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride, groups=hidden,
                        act=nn.ReLU6),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = _make_divisible(32 * scale)
        features = [_ConvBNReLU(3, in_c, 3, 2, act=nn.ReLU6)]
        for t, ch, n, s in cfg:
            out_c = _make_divisible(ch * scale)
            for i in range(n):
                features.append(InvertedResidual(in_c, out_c,
                                                 s if i == 0 else 1, t))
                in_c = out_c
        last = _make_divisible(1280 * max(1.0, scale))
        features.append(_ConvBNReLU(in_c, last, 1, act=nn.ReLU6))
        self.features = nn.Sequential(*features)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ... import tensor as T

            x = self.classifier(T.flatten(x, 1))
        return x


class _SqueezeExcite(nn.Layer):
    def __init__(self, ch, squeeze=4):
        super().__init__()
        mid = _make_divisible(ch // squeeze)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, mid, 1)
        self.fc2 = nn.Conv2D(mid, ch, 1)

    def forward(self, x):
        s = self.pool(x)
        s = nn.functional.relu(self.fc1(s))
        s = nn.functional.hardsigmoid(self.fc2(s), slope=0.2, offset=0.5)
        return x * s


class _V3Block(nn.Layer):
    def __init__(self, in_c, mid_c, out_c, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        act_layer = nn.Hardswish if act == "hardswish" else nn.ReLU
        layers = []
        if mid_c != in_c:
            layers.append(_ConvBNReLU(in_c, mid_c, 1, act=act_layer))
        layers.append(_ConvBNReLU(mid_c, mid_c, k, stride, groups=mid_c,
                                  act=act_layer))
        self.pre = nn.Sequential(*layers)
        self.se = _SqueezeExcite(mid_c) if use_se else None
        self.post = nn.Sequential(
            nn.Conv2D(mid_c, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c))

    def forward(self, x):
        out = self.pre(x)
        if self.se is not None:
            out = self.se(out)
        out = self.post(out)
        return x + out if self.use_res else out


_V3_LARGE = [
    # k, mid, out, se, act, stride
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]

_V3_SMALL = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        layers = [_ConvBNReLU(3, in_c, 3, 2, act=nn.Hardswish)]
        for k, mid, out, se, act, s in cfg:
            mid_c = _make_divisible(mid * scale)
            out_c = _make_divisible(out * scale)
            layers.append(_V3Block(in_c, mid_c, out_c, k, s, se, act))
            in_c = out_c
        last_conv = _make_divisible(cfg[-1][1] * scale)
        layers.append(_ConvBNReLU(in_c, last_conv, 1, act=nn.Hardswish))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_c), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ... import tensor as T

            x = self.classifier(T.flatten(x, 1))
        return x


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 1024, scale, num_classes, with_pool)


def _maybe_pretrained(model, pretrained, arch):
    if pretrained:
        from ._pretrained import load_pretrained

        load_pretrained(model, arch)
    return model


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return _maybe_pretrained(MobileNetV1(scale=scale, **kwargs),
                             pretrained, f"mobilenetv1_{float(scale)}")


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return _maybe_pretrained(MobileNetV2(scale=scale, **kwargs),
                             pretrained, f"mobilenetv2_{float(scale)}")


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return _maybe_pretrained(MobileNetV3Small(scale=scale, **kwargs),
                             pretrained, f"mobilenetv3_small_{float(scale)}")


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return _maybe_pretrained(MobileNetV3Large(scale=scale, **kwargs),
                             pretrained, f"mobilenetv3_large_{float(scale)}")
