"""Vision transforms (reference: python/paddle/vision/transforms/*).

Operate on numpy CHW float arrays (the loader's host-side format) so the
input pipeline stays off-device until one async transfer per batch.
"""
from __future__ import annotations

import numbers

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "ContrastTransform",
           "SaturationTransform", "HueTransform", "ColorJitter", "Pad",
           "RandomRotation", "Grayscale", "RandomResizedCrop",
           "normalize", "resize", "to_tensor", "hflip", "vflip", "crop",
           "center_crop"]


def _chw(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[None]
    elif img.ndim == 3 and img.shape[-1] in (1, 3, 4) and \
            img.shape[0] not in (1, 3, 4):
        img = img.transpose(2, 0, 1)
    return img.astype(np.float32)


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        img = _chw(img)
        if self.data_format == "HWC":
            img = img.transpose(1, 2, 0)
        return img


to_tensor = ToTensor()


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        self.data_format = data_format
        shape = (-1, 1, 1) if data_format == "CHW" else (1, 1, -1)
        self.mean = np.asarray(mean, np.float32).reshape(shape)
        self.std = np.asarray(std, np.float32).reshape(shape)

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            img = _chw(img)
        return (img - self.mean) / self.std


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format, to_rgb)(img)


def _resize_np(img, size):
    """Nearest+linear resize on CHW numpy, no PIL dependency."""
    c, h, w = img.shape
    if isinstance(size, numbers.Number):
        if h < w:
            oh, ow = int(size), int(size * w / h)
        else:
            oh, ow = int(size * h / w), int(size)
    else:
        oh, ow = size
    ys = np.clip((np.arange(oh) + 0.5) * h / oh - 0.5, 0, h - 1)
    xs = np.clip((np.arange(ow) + 0.5) * w / ow - 0.5, 0, w - 1)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[None, :, None]
    wx = (xs - x0)[None, None, :]
    a = img[:, y0][:, :, x0]
    b = img[:, y0][:, :, x1]
    cc = img[:, y1][:, :, x0]
    d = img[:, y1][:, :, x1]
    return (a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx
            + cc * wy * (1 - wx) + d * wy * wx).astype(img.dtype)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return _resize_np(_chw(img), self.size)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def crop(img, top, left, height, width):
    return _chw(img)[:, top:top + height, left:left + width]


def center_crop(img, output_size):
    img = _chw(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    th, tw = output_size
    h, w = img.shape[1:]
    top = max((h - th) // 2, 0)
    left = max((w - tw) // 2, 0)
    return crop(img, top, left, th, tw)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.padding = padding

    def _apply_image(self, img):
        img = _chw(img)
        if self.padding:
            p = self.padding
            p = (p, p) if isinstance(p, numbers.Number) else p
            img = np.pad(img, [(0, 0), (p[1], p[1]), (p[0], p[0])])
        h, w = img.shape[1:]
        th, tw = self.size
        top = np.random.randint(0, max(h - th, 0) + 1)
        left = np.random.randint(0, max(w - tw, 0) + 1)
        return crop(img, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        img = _chw(img)
        c, h, w = img.shape
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = np.random.randint(0, h - ch + 1)
                left = np.random.randint(0, w - cw + 1)
                return _resize_np(crop(img, top, left, ch, cw), self.size)
        return _resize_np(center_crop(img, min(h, w)), self.size)


def hflip(img):
    return _chw(img)[:, :, ::-1].copy()


def vflip(img):
    return _chw(img)[:, ::-1].copy()


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return hflip(img)
        return _chw(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return vflip(img)
        return _chw(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return np.asarray(img).transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        img = _chw(img)
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = img.mean(0, keepdims=True)
        return np.clip((img - gray) * f + gray, 0, 1)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        f = np.random.uniform(-self.value, self.value)
        return adjust_hue(img, f)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))
        if hue:
            self.ts.append(HueTransform(hue))

    def _apply_image(self, img):
        for t in np.random.permutation(self.ts).tolist():
            img = t(img)
        return img


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        p = padding
        if isinstance(p, numbers.Number):
            p = (p, p, p, p)
        elif len(p) == 2:
            p = (p[0], p[1], p[0], p[1])
        self.padding = p
        self.fill = fill
        self.mode = padding_mode

    def _apply_image(self, img):
        img = _chw(img)
        l, t, r, b = self.padding
        if self.mode == "constant":
            return np.pad(img, [(0, 0), (t, b), (l, r)],
                          constant_values=self.fill)
        mode = {"reflect": "reflect", "edge": "edge",
                "symmetric": "symmetric"}[self.mode]
        return np.pad(img, [(0, 0), (t, b), (l, r)], mode=mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees

    def _apply_image(self, img):
        img = _chw(img)
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        c, h, w = img.shape
        cy, cx = (h - 1) / 2, (w - 1) / 2
        yy, xx = np.mgrid[0:h, 0:w]
        ys = cy + (yy - cy) * np.cos(angle) - (xx - cx) * np.sin(angle)
        xs = cx + (yy - cy) * np.sin(angle) + (xx - cx) * np.cos(angle)
        yi = np.clip(np.round(ys).astype(int), 0, h - 1)
        xi = np.clip(np.round(xs).astype(int), 0, w - 1)
        out = img[:, yi, xi]
        mask = (ys < 0) | (ys > h - 1) | (xs < 0) | (xs > w - 1)
        out[:, mask] = 0
        return out


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.n = num_output_channels

    def _apply_image(self, img):
        img = _chw(img)
        if img.shape[0] == 3:
            g = (0.2989 * img[0] + 0.587 * img[1] + 0.114 * img[2])[None]
        else:
            g = img[:1]
        return np.repeat(g, self.n, 0) if self.n > 1 else g


# ---- functional transforms (reference vision/transforms/functional.py) ----
def adjust_brightness(img, brightness_factor):
    return np.clip(_chw(img) * brightness_factor, 0, 1)


def adjust_contrast(img, contrast_factor):
    img = _chw(img)
    mean = img.mean()
    return np.clip((img - mean) * contrast_factor + mean, 0, 1)


def adjust_hue(img, hue_factor):
    """hue_factor in [-0.5, 0.5]; same channel roll-mix emulation as
    HueTransform."""
    img = _chw(img)
    if img.shape[0] != 3:
        return img
    rolled = np.roll(img, 1, axis=0)
    return np.clip(img * (1 - abs(hue_factor)) + rolled * abs(hue_factor),
                   0, 1)


def pad(img, padding, fill=0, padding_mode="constant"):
    return Pad(padding, fill, padding_mode)._apply_image(img)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Deterministic rotation by `angle` degrees (nearest sampling)."""
    img = _chw(img)
    rad = np.deg2rad(angle)
    c, h, w = img.shape
    if center is None:
        cy, cx = (h - 1) / 2, (w - 1) / 2
    else:
        cx, cy = center
    yy, xx = np.mgrid[0:h, 0:w]
    ys = cy + (yy - cy) * np.cos(rad) - (xx - cx) * np.sin(rad)
    xs = cx + (yy - cy) * np.sin(rad) + (xx - cx) * np.cos(rad)
    yi = np.clip(np.round(ys).astype(int), 0, h - 1)
    xi = np.clip(np.round(xs).astype(int), 0, w - 1)
    out = img[:, yi, xi].copy()
    mask = (ys < 0) | (ys > h - 1) | (xs < 0) | (xs > w - 1)
    out[:, mask] = fill
    return out


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)._apply_image(img)


__all__ += ["adjust_brightness", "adjust_contrast", "adjust_hue", "pad",
            "rotate", "to_grayscale"]
