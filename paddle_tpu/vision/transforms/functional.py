"""paddle.vision.transforms.functional (reference: python/paddle/vision/
transforms/functional.py — the functional forms user pipelines import as
`import paddle.vision.transforms.functional as F`).

The implementations live in the transforms package; this module restores
the reference import path and the two functional forms that only had
class equivalents (to_tensor with an explicit data_format arg,
adjust_saturation with a deterministic factor).
"""
from __future__ import annotations

import numpy as np

from . import _chw
from . import (  # noqa: F401
    adjust_brightness, adjust_contrast, adjust_hue, center_crop, crop,
    hflip, normalize, pad, resize, rotate, to_grayscale, vflip,
)

__all__ = ["to_tensor", "resize", "pad", "crop", "center_crop", "hflip",
           "vflip", "adjust_brightness", "adjust_contrast",
           "adjust_saturation", "adjust_hue", "rotate", "to_grayscale",
           "normalize"]


def to_tensor(pic, data_format="CHW"):
    """PIL/ndarray HWC uint8 -> float CHW ndarray in [0, 1] (reference
    functional.py:47)."""
    img = np.asarray(pic)
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 255.0
    img = _chw(img)
    if data_format == "HWC":
        img = img.transpose(1, 2, 0)
    return img


def adjust_saturation(img, saturation_factor):
    """Blend with the grayscale image by a FIXED factor (reference
    functional.py:443 — the class transform draws the factor randomly,
    the functional form takes it)."""
    img = np.asarray(img, dtype=np.float32)
    chw = _chw(img)
    gray = chw.mean(0, keepdims=True)
    out = np.clip((chw - gray) * saturation_factor + gray, 0,
                  255.0 if img.max() > 1.0 else 1.0)
    return out if img.ndim == 3 and img.shape[0] in (1, 3) else \
        out.transpose(1, 2, 0)
