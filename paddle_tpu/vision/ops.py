"""paddle.vision.ops — detection operators.

Reference: python/paddle/vision/ops.py (yolo_box:253, deform_conv2d:430,
psroi_pool:918, roi_pool:1033, roi_align:1160, nms:1376, read_file,
decode_jpeg). TPU-native design: deform_conv2d and yolo_box are fully
vectorized jnp (jittable, differentiable — bilinear sampling via gathers,
the contraction rides the MXU). RoI ops loop over rois in Python with
vectorized per-roi math (detection postprocessing is host-driven in the
reference too: dynamic roi counts don't belong inside an XLA program), and
nms is eager greedy suppression.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply
from ..core.tensor import Tensor
from ..nn.layer.activation import ReLU as _ReLU
from ..nn.layer.container import Sequential as _Sequential
from ..nn.layer.conv import Conv2D as _Conv2D
from ..nn.layer.layers import Layer
from ..nn.layer.norm import BatchNorm2D as _BatchNorm2D

__all__ = [
    "ConvNormActivation",
    "yolo_loss", "yolo_box", "deform_conv2d", "DeformConv2D",
    "read_file", "decode_jpeg", "roi_pool", "RoIPool", "psroi_pool",
    "PSRoIPool", "roi_align", "RoIAlign", "nms",
]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# deformable convolution (v1: mask=None, v2: modulated)
# ---------------------------------------------------------------------------
def _bilinear_sample(img, py, px):
    """img [C, H, W]; py/px [...]: bilinear values with zero padding."""
    C, H, W = img.shape
    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    wy1 = py - y0
    wx1 = px - x0
    vals = 0.0
    for dy, wy in ((0, 1 - wy1), (1, wy1)):
        for dx, wx in ((0, 1 - wx1), (1, wx1)):
            yy = y0 + dy
            xx = x0 + dx
            inb = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
            yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
            v = img[:, yi, xi]                       # [C, ...]
            vals = vals + v * (wy * wx * inb)[None]
    return vals


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """y(p) = sum_k w_k * x(p + p_k + dp_k) * dm_k (reference vision/ops.py:430)."""
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    def _f(xv, off, w, m, b):
        N, Cin, H, W = xv.shape
        Cout, Cin_g, kh, kw = w.shape
        Hout = (H + 2 * padding[0] - dilation[0] * (kh - 1) - 1) \
            // stride[0] + 1
        Wout = (W + 2 * padding[1] - dilation[1] * (kw - 1) - 1) \
            // stride[1] + 1
        dg = deformable_groups
        # base sampling grid per kernel tap: [kh*kw, Hout, Wout]
        oy = jnp.arange(Hout) * stride[0] - padding[0]
        ox = jnp.arange(Wout) * stride[1] - padding[1]
        ky = jnp.arange(kh) * dilation[0]
        kx = jnp.arange(kw) * dilation[1]
        base_y = (oy[None, :, None] + ky[:, None, None])[:, None]  # kh,1,Ho,1
        base_x = (ox[None, None, :] + kx[:, None, None])[None]     # 1,kw,1,Wo
        base_y = jnp.broadcast_to(base_y, (kh, kw, Hout, Wout))
        base_x = jnp.broadcast_to(base_x, (kh, kw, Hout, Wout))
        off = off.reshape(N, dg, kh, kw, 2, Hout, Wout)
        py = base_y[None, None] + off[:, :, :, :, 0]   # [N,dg,kh,kw,Ho,Wo]
        px = base_x[None, None] + off[:, :, :, :, 1]
        m = (jnp.ones((N, dg, kh, kw, Hout, Wout), xv.dtype) if m is None
             else m.reshape(N, dg, kh, kw, Hout, Wout))

        cg = Cin // dg  # channels per deformable group

        def sample_image(img, py_i, px_i, m_i):
            # img [Cin,H,W]; py_i/m_i [dg,kh,kw,Ho,Wo]
            def per_group(g_img, g_py, g_px, g_m):
                return _bilinear_sample(g_img, g_py, g_px) * g_m[None]

            v = jax.vmap(per_group)(img.reshape(dg, cg, H, W),
                                    py_i, px_i, m_i)
            return v.reshape(Cin, kh, kw, Hout, Wout)

        cols = jax.vmap(sample_image)(xv, py, px, m)  # [N,Cin,kh,kw,Ho,Wo]
        # grouped contraction on the MXU
        cols = cols.reshape(N, groups, Cin // groups, kh, kw, Hout, Wout)
        w = w.reshape(groups, Cout // groups, Cin_g, kh, kw)
        out = jnp.einsum("ngiabcd,goiab->ngocd", cols, w)
        out = out.reshape(N, Cout, Hout, Wout)
        if b is not None:
            out = out + b[None, :, None, None]
        return out

    extras = []
    if mask is not None:
        extras.append(mask)
    if bias is not None:
        extras.append(bias)

    def op(xv, off, w, *rest):
        rest = list(rest)
        m = rest.pop(0) if mask is not None else None
        b = rest.pop(0) if bias is not None else None
        return _f(xv, off, w, m, b)

    op.__name__ = "deform_conv2d"
    return apply(op, x, offset, weight, *extras)


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        from ..nn.initializer import XavierUniform

        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, *ks], attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=self._stride, padding=self._padding,
                             dilation=self._dilation,
                             deformable_groups=self._deformable_groups,
                             groups=self._groups, mask=mask)


# ---------------------------------------------------------------------------
# yolo
# ---------------------------------------------------------------------------
def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head output to boxes+scores (vision/ops.py:253)."""
    xv = _val(x).astype(jnp.float32)
    img = _val(img_size).astype(jnp.float32)          # [N, 2] (h, w)
    an = np.asarray(anchors, np.float32).reshape(-1, 2)
    na = len(an)
    N, C, H, W = xv.shape
    if iou_aware:
        ioup = jax.nn.sigmoid(xv[:, :na].reshape(N, na, 1, H, W))
        xv = xv[:, na:]
    xv = xv.reshape(N, na, 5 + class_num, H, W)
    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    bx = (jax.nn.sigmoid(xv[:, :, 0]) * scale_x_y
          - 0.5 * (scale_x_y - 1.0) + gx) / W
    by = (jax.nn.sigmoid(xv[:, :, 1]) * scale_x_y
          - 0.5 * (scale_x_y - 1.0) + gy) / H
    input_h = downsample_ratio * H
    input_w = downsample_ratio * W
    bw = jnp.exp(xv[:, :, 2]) * an[None, :, 0, None, None] / input_w
    bh = jnp.exp(xv[:, :, 3]) * an[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(xv[:, :, 4])
    if iou_aware:
        conf = conf ** (1 - iou_aware_factor) \
            * ioup[:, :, 0] ** iou_aware_factor
    probs = jax.nn.sigmoid(xv[:, :, 5:]) * conf[:, :, None]

    img_h = img[:, 0][:, None, None, None]
    img_w = img[:, 1][:, None, None, None]
    x1 = (bx - bw / 2) * img_w
    y1 = (by - bh / 2) * img_h
    x2 = (bx + bw / 2) * img_w
    y2 = (by + bh / 2) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0)
        y1 = jnp.clip(y1, 0)
        x2 = jnp.minimum(x2, img_w - 1)
        y2 = jnp.minimum(y2, img_h - 1)
    keep = (conf > conf_thresh).astype(jnp.float32)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]
    scores = probs * keep[:, :, None]
    boxes = boxes.reshape(N, na * H * W, 4)           # [N,na,H,W,4] flat
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(N, na * H * W,
                                                     class_num)
    return Tensor(boxes), Tensor(scores)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (vision/ops.py yolo_loss). Vectorized anchor/cell
    assignment via one-hot masks; returns per-image loss [N]."""
    xv = _val(x).astype(jnp.float32)
    gtb = _val(gt_box).astype(jnp.float32)            # [N, B, 4] xywh (rel)
    gtl = _val(gt_label).astype(jnp.int32)            # [N, B]
    an_all = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask_idx = np.asarray(anchor_mask, np.int64)
    an = an_all[mask_idx]
    na = len(mask_idx)
    N, C, H, W = xv.shape
    xv = xv.reshape(N, na, 5 + class_num, H, W)
    input_w = downsample_ratio * W
    input_h = downsample_ratio * H

    # decode predicted boxes (relative units) for the ignore mask
    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    px = (jax.nn.sigmoid(xv[:, :, 0]) + gx) / W
    py = (jax.nn.sigmoid(xv[:, :, 1]) + gy) / H
    pw = jnp.exp(xv[:, :, 2]) * an[None, :, 0, None, None] / input_w
    ph = jnp.exp(xv[:, :, 3]) * an[None, :, 1, None, None] / input_h

    valid = (gtb[..., 2] > 0) & (gtb[..., 3] > 0)     # [N, B]

    def iou_xywh(x1, y1, w1, h1, x2, y2, w2, h2):
        l1, r1 = x1 - w1 / 2, x1 + w1 / 2
        t1, b1 = y1 - h1 / 2, y1 + h1 / 2
        l2, r2 = x2 - w2 / 2, x2 + w2 / 2
        t2, b2 = y2 - h2 / 2, y2 + h2 / 2
        iw = jnp.clip(jnp.minimum(r1, r2) - jnp.maximum(l1, l2), 0)
        ih = jnp.clip(jnp.minimum(b1, b2) - jnp.maximum(t1, t2), 0)
        inter = iw * ih
        union = w1 * h1 + w2 * h2 - inter
        return inter / jnp.maximum(union, 1e-10)

    # ignore mask: pred boxes overlapping any gt above thresh aren't negatives
    iou_all = iou_xywh(
        px[..., None], py[..., None], pw[..., None], ph[..., None],
        gtb[:, None, None, None, :, 0], gtb[:, None, None, None, :, 1],
        gtb[:, None, None, None, :, 2], gtb[:, None, None, None, :, 3])
    iou_all = jnp.where(valid[:, None, None, None, :], iou_all, 0.0)
    ignore = (iou_all.max(-1) > ignore_thresh)        # [N,na,H,W]

    # responsible anchor (over the FULL anchor set) + cell per gt
    gw_in = gtb[..., 2] * input_w
    gh_in = gtb[..., 3] * input_h
    iou_an = iou_xywh(0.0, 0.0, gw_in[..., None], gh_in[..., None],
                      0.0, 0.0, an_all[None, None, :, 0],
                      an_all[None, None, :, 1])       # [N,B,num_anchors]
    best = jnp.argmax(iou_an, axis=-1)                # [N, B]
    # position of best anchor inside this head's mask (-1 if elsewhere)
    in_mask = jnp.zeros_like(best) - 1
    for pos, a_idx in enumerate(mask_idx):
        in_mask = jnp.where(best == a_idx, pos, in_mask)
    ci = jnp.clip((gtb[..., 0] * W).astype(jnp.int32), 0, W - 1)
    cj = jnp.clip((gtb[..., 1] * H).astype(jnp.int32), 0, H - 1)
    resp = valid & (in_mask >= 0)                     # [N, B]

    # scatter gt targets onto the [na, H, W] grid via one-hot products
    oh_a = jax.nn.one_hot(jnp.clip(in_mask, 0), na)   # [N,B,na]
    oh_y = jax.nn.one_hot(cj, H)
    oh_x = jax.nn.one_hot(ci, W)
    sel = (oh_a[:, :, :, None, None] * oh_y[:, :, None, :, None]
           * oh_x[:, :, None, None, :]) \
        * resp[:, :, None, None, None]                # [N,B,na,H,W]
    obj = sel.max(1)                                  # [N,na,H,W]

    tx = gtb[..., 0] * W - ci
    ty = gtb[..., 1] * H - cj
    an_w = an_all[:, 0][mask_idx][None, None] / input_w
    an_h = an_all[:, 1][mask_idx][None, None] / input_h
    aw_per_gt = jnp.take(an_all[:, 0], best, axis=0) / input_w
    ah_per_gt = jnp.take(an_all[:, 1], best, axis=0) / input_h
    tw = jnp.log(jnp.maximum(gtb[..., 2] / jnp.maximum(aw_per_gt, 1e-9),
                             1e-9))
    th = jnp.log(jnp.maximum(gtb[..., 3] / jnp.maximum(ah_per_gt, 1e-9),
                             1e-9))
    box_scale = 2.0 - gtb[..., 2] * gtb[..., 3]
    score = (jnp.ones_like(tx) if gt_score is None
             else _val(gt_score).astype(jnp.float32))
    wgt = score * box_scale                           # [N, B]

    def bce(logit, target):
        return jnp.maximum(logit, 0) - logit * target \
            + jnp.log1p(jnp.exp(-jnp.abs(logit)))

    def scat(v):  # [N,B] -> [N,na,H,W]
        return (sel * v[:, :, None, None, None]).sum(1)

    loss_xy = (bce(xv[:, :, 0], scat(tx)) * scat(wgt)
               + bce(xv[:, :, 1], scat(ty)) * scat(wgt)) * obj
    loss_wh = ((xv[:, :, 2] - scat(tw)) ** 2
               + (xv[:, :, 3] - scat(th)) ** 2) * scat(wgt) * 0.5 * obj
    smooth = 1.0 / class_num if use_label_smooth else 0.0
    tcls = jax.nn.one_hot(gtl, class_num) * (1 - smooth) \
        + smooth / max(class_num - 1, 1) * (1 - jax.nn.one_hot(gtl,
                                                               class_num))
    cls_target = jnp.einsum("nbahw,nbc->nachw", sel, tcls)
    loss_cls = (bce(xv[:, :, 5:], cls_target)
                * obj[:, :, None]).sum((1, 2, 3, 4))
    obj_loss = bce(xv[:, :, 4], obj)
    loss_obj = (obj_loss * obj).sum((1, 2, 3)) \
        + (obj_loss * (1 - obj) * (1 - ignore)).sum((1, 2, 3))
    total = (loss_xy.sum((1, 2, 3)) + loss_wh.sum((1, 2, 3))
             + loss_obj + loss_cls)
    return Tensor(total)


# ---------------------------------------------------------------------------
# RoI ops (eager: roi counts are data-dependent, host-driven postprocessing)
# ---------------------------------------------------------------------------
def _split_rois(boxes, boxes_num):
    bn = [int(v) for v in np.asarray(_val(boxes_num))]
    img_idx = np.repeat(np.arange(len(bn)), bn)
    return _val(boxes), img_idx


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Mask R-CNN RoIAlign (vision/ops.py:1160): average of bilinear
    samples per bin; adaptive sample count when sampling_ratio=-1."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    xv = _val(x)
    rois, img_idx = _split_rois(boxes, boxes_num)
    off = 0.5 if aligned else 0.0
    outs = []
    for r in range(rois.shape[0]):
        x1, y1, x2, y2 = [float(v) for v in np.asarray(rois[r])]
        img = xv[int(img_idx[r])]
        rx = x1 * spatial_scale - off
        ry = y1 * spatial_scale - off
        rw = x2 * spatial_scale - off - rx
        rh = y2 * spatial_scale - off - ry
        if not aligned:
            rw = max(rw, 1.0)
            rh = max(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        gy = sampling_ratio if sampling_ratio > 0 \
            else max(1, math.ceil(rh / ph))
        gx = sampling_ratio if sampling_ratio > 0 \
            else max(1, math.ceil(rw / pw))
        sy = ry + (jnp.arange(ph)[:, None] + (jnp.arange(gy) + 0.5)[None]
                   / gy) * bin_h                      # [ph, gy]
        sx = rx + (jnp.arange(pw)[:, None] + (jnp.arange(gx) + 0.5)[None]
                   / gx) * bin_w                      # [pw, gx]
        py = jnp.broadcast_to(sy[:, None, :, None], (ph, pw, gy, gx))
        px = jnp.broadcast_to(sx[None, :, None, :], (ph, pw, gy, gx))
        vals = _bilinear_sample(img, py, px)          # [C, ph, pw, gy, gx]
        outs.append(vals.mean((-1, -2)))
    out = jnp.stack(outs) if outs else jnp.zeros(
        (0, xv.shape[1], ph, pw), xv.dtype)
    return Tensor(out)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Quantized max pooling per RoI bin (vision/ops.py:1033)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    xv = _val(x)
    H, W = xv.shape[-2:]
    rois, img_idx = _split_rois(boxes, boxes_num)
    outs = []
    for r in range(rois.shape[0]):
        x1, y1, x2, y2 = [float(v) for v in np.asarray(rois[r])]
        img = xv[int(img_idx[r])]
        rx1 = int(round(x1 * spatial_scale))
        ry1 = int(round(y1 * spatial_scale))
        rx2 = int(round(x2 * spatial_scale))
        ry2 = int(round(y2 * spatial_scale))
        rh = max(ry2 - ry1 + 1, 1)
        rw = max(rx2 - rx1 + 1, 1)
        bins = []
        for i in range(ph):
            hs = min(max(ry1 + int(np.floor(i * rh / ph)), 0), H)
            he = min(max(ry1 + int(np.ceil((i + 1) * rh / ph)), 0), H)
            row = []
            for j in range(pw):
                ws = min(max(rx1 + int(np.floor(j * rw / pw)), 0), W)
                we = min(max(rx1 + int(np.ceil((j + 1) * rw / pw)), 0), W)
                if he > hs and we > ws:
                    row.append(img[:, hs:he, ws:we].max((-1, -2)))
                else:
                    row.append(jnp.zeros(img.shape[0], img.dtype))
            bins.append(jnp.stack(row, -1))
        outs.append(jnp.stack(bins, -2))
    out = jnp.stack(outs) if outs else jnp.zeros(
        (0, xv.shape[1], ph, pw), xv.dtype)
    return Tensor(out)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pooling (vision/ops.py:918):
    channel block (i,j) feeds output bin (i,j)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    xv = _val(x)
    C, H, W = xv.shape[1:]
    assert C % (ph * pw) == 0, "channels must be divisible by ph*pw"
    co = C // (ph * pw)
    rois, img_idx = _split_rois(boxes, boxes_num)
    outs = []
    for r in range(rois.shape[0]):
        x1, y1, x2, y2 = [float(v) for v in np.asarray(rois[r])]
        # reference layout: channel (c*ph + i)*pw + j -> [co, ph, pw] blocks
        img = xv[int(img_idx[r])].reshape(co, ph, pw, H, W)
        rx1 = round(x1 * spatial_scale)
        ry1 = round(y1 * spatial_scale)
        rw = max(round(x2 * spatial_scale) - rx1, 0.1)
        rh = max(round(y2 * spatial_scale) - ry1, 0.1)
        out = jnp.zeros((co, ph, pw), xv.dtype)
        for i in range(ph):
            hs = min(max(int(np.floor(ry1 + i * rh / ph)), 0), H)
            he = min(max(int(np.ceil(ry1 + (i + 1) * rh / ph)), 0), H)
            for j in range(pw):
                ws = min(max(int(np.floor(rx1 + j * rw / pw)), 0), W)
                we = min(max(int(np.ceil(rx1 + (j + 1) * rw / pw)), 0), W)
                if he > hs and we > ws:
                    out = out.at[:, i, j].set(
                        img[:, i, j, hs:he, ws:we].mean((-1, -2)))
        outs.append(out)
    out = jnp.stack(outs) if outs else jnp.zeros((0, co, ph, pw), xv.dtype)
    return Tensor(out)


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


# ---------------------------------------------------------------------------
# nms
# ---------------------------------------------------------------------------
def _iou_matrix(b):
    area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = np.maximum(b[:, None, :2], b[None, :, :2])
    rb = np.minimum(b[:, None, 2:], b[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    return inter / np.maximum(area[:, None] + area[None] - inter, 1e-10)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy (optionally per-category) NMS; returns kept indices sorted by
    score (vision/ops.py:1376)."""
    b = np.asarray(_val(boxes), np.float32)
    n = b.shape[0]
    sc = (np.asarray(_val(scores), np.float32) if scores is not None
          else None)

    def greedy(idxs):
        order = idxs if sc is None else idxs[np.argsort(-sc[idxs])]
        iou = _iou_matrix(b[order])  # subset only: O(k^2), not O(n^2)
        keep = []
        alive = np.ones(len(order), bool)
        for i in range(len(order)):
            if not alive[i]:
                continue
            keep.append(order[i])
            alive &= ~(iou[i] > iou_threshold) \
                | (np.arange(len(order)) <= i)
        return np.asarray(keep, np.int64)

    if category_idxs is None:
        kept = greedy(np.arange(n))
    else:
        cats = np.asarray(_val(category_idxs))
        parts = [greedy(np.nonzero(cats == c)[0]) for c in categories]
        kept = np.concatenate([p for p in parts if len(p)]) \
            if parts else np.zeros(0, np.int64)
        if sc is not None and len(kept):
            kept = kept[np.argsort(-sc[kept])]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept))


# ---------------------------------------------------------------------------
# file io
# ---------------------------------------------------------------------------
def read_file(filename, name=None):
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.frombuffer(data, dtype=jnp.uint8))


def decode_jpeg(x, mode="unchanged", name=None):
    """JPEG bytes -> [C, H, W] uint8 (PIL-backed host decode)."""
    import io

    from PIL import Image

    raw = bytes(np.asarray(_val(x), np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


class ConvNormActivation(_Sequential):
    """Conv-Norm-Activation block (reference vision/ops.py
    ConvNormActivation, itself modeled on torchvision misc.py): a
    Sequential of Conv2D [+ norm_layer] [+ activation_layer], with the
    reference's same-padding default and bias-iff-no-norm rule."""

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1,
                 padding=None, groups=1, norm_layer=_BatchNorm2D,
                 activation_layer=_ReLU, dilation=1, bias=None):
        if padding is None:
            padding = (kernel_size - 1) // 2 * dilation
        if bias is None:
            bias = norm_layer is None
        layers = [_Conv2D(in_channels, out_channels, kernel_size, stride,
                          padding, dilation=dilation, groups=groups,
                          bias_attr=None if bias else False)]
        if norm_layer is not None:
            layers.append(norm_layer(out_channels))
        if activation_layer is not None:
            layers.append(activation_layer())
        super().__init__(*layers)
