"""DataLoader (reference: python/paddle/fluid/dataloader/dataloader_iter.py).

TPU-native input pipeline: worker THREADS (numpy ops release the GIL) fill a
bounded prefetch queue so host-side batch assembly overlaps device compute;
the device transfer itself is async under XLA.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from ..core.tensor import Tensor
from ..runtime import tracing as _tracing
from ..runtime.resilience import fault_point, record_fault
from .dataset import IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()


class _WorkerError:
    def __init__(self, exc):
        self.exc = exc


class _Staged:
    """Marker for a batch parked in the C++ staging pool."""

    def __init__(self, slot, meta, treedef):
        self.slot = slot
        self.meta = meta
        self.treedef = treedef


def _numpy_collate(batch):
    """default_collate_fn variant that keeps leaves as numpy (stageable)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        return tuple(_numpy_collate([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: _numpy_collate([b[k] for b in batch]) for k in sample}
    return None  # not stageable (Tensors / arbitrary objects)


def numpy_collate_or_default(batch):
    """`_numpy_collate` when every leaf is numpy-able, else the normal
    `default_collate_fn`. The sharded prefetch tier collates through
    this so stageable batches stay HOST-side (one commit: local rows →
    global array) while exotic samples keep today's semantics."""
    import jax

    out = _numpy_collate(batch)
    leaves = jax.tree_util.tree_flatten(
        out, is_leaf=lambda x: x is None)[0]
    if out is None or not all(isinstance(a, np.ndarray) for a in leaves):
        return default_collate_fn(batch)
    return out


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        from .. import tensor as T

        return T.stack(batch, axis=0)
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, use_staging_pool=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        # route batches through the C++ staging ring (csrc/staging_pool.cpp);
        # only applies with worker threads + the default (numpy-able) collate
        self.use_staging_pool = (bool(use_staging_pool)
                                 and collate_fn is None)
        self._pool = None
        self._pool_lock = threading.Lock()
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
                self.batch_size = batch_size

    def __len__(self):
        if self._iterable:
            raise TypeError("length of IterableDataset-backed loader unknown")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    # ---- iteration -------------------------------------------------------
    def _fetch(self, indices):
        return self.collate_fn([self.dataset[i] for i in indices])

    @property
    def _window(self):
        """Prefetch depth; also the staging-ring size (their equality is
        load-bearing: n_slots >= max_ahead keeps the pipeline live)."""
        return max(2, self.num_workers * self.prefetch_factor)

    def _fetch_staged(self, indices):
        """Collate to numpy and park the batch in the staging ring.
        Falls back to the normal path for unstageable/oversized batches."""
        import jax

        from ..runtime.staging import _align

        items = [self.dataset[i] for i in indices]  # fetched exactly once
        batch = _numpy_collate(items)
        leaves, treedef = (jax.tree_util.tree_flatten(
            batch, is_leaf=lambda x: x is None) if batch is not None
            else ([None], None))
        if not all(isinstance(a, np.ndarray) for a in leaves):
            return self.collate_fn(items)
        need = sum(_align(a.nbytes) for a in leaves)
        # size the ring from the NOMINAL batch size, not whichever (possibly
        # ragged, out-of-order) batch happens to arrive first
        nominal = need * max(1, self.batch_size or 1) / max(1, len(indices))
        pool = self._ensure_pool(nominal)
        if pool is None or need > pool.slot_bytes:
            return self.collate_fn(items)
        slot = pool.acquire_write()
        if slot < 0:
            return self.collate_fn(items)
        meta = pool.write_arrays(slot, leaves)
        return _Staged(slot, meta, treedef)

    def _ensure_pool(self, nominal_batch_bytes):
        from ..runtime.staging import StagingPool

        with self._pool_lock:
            if self._pool is None:
                slot_bytes = int(nominal_batch_bytes * 1.25) + 64
                try:
                    self._pool = StagingPool(self._window, slot_bytes)
                except Exception:
                    # no g++, csrc missing from an installed wheel, alloc
                    # failure, ... — staging is an optimization, fall back
                    self.use_staging_pool = False
            return self._pool

    def _unstage(self, staged):
        """Device-put the slot's views, then recycle the slot.
        Span-traced ("io/unstage"): the staging-ring consume cost is
        part of the data-wait story the timeline decomposes."""
        import jax

        with _tracing.span("unstage", "io", slot=staged.slot):
            return self._unstage_impl(jax, staged)

    def _unstage_impl(self, jax, staged):
        views = self._pool.view_arrays(staged.slot, staged.meta)
        from . import prefetch as _prefetch

        if _prefetch.staging_direct_ok():
            # ONE copy, ring → device, barriered before the slot is
            # recycled — opt-in per backend (see staging_direct_ok: the
            # operator asserts block_until_ready is a real barrier
            # there; the aliasing probe vetoes backends where the slot
            # would alias live device memory). Shares the measured-h2d
            # contract with every other commit site.
            tensors = [Tensor(d) for d in _prefetch.commit_arrays(
                views, kind="unstage_direct")]
        else:
            # synchronous host copy before releasing: the CPU backend
            # zero-copy ALIASES aligned buffers, and block_until_ready can
            # return early on the axon tunnel — np.array is the only release
            # barrier that holds on every backend. The copy runs at memcpy
            # speed on slot-aligned memory and is what the device transfer
            # consumes asynchronously.
            tensors = [Tensor(np.array(v)) for v in views]
        self._pool.release(staged.slot)
        return jax.tree_util.tree_unflatten(staged.treedef, tensors)

    def _check_timeout(self, t0, batch):
        """`timeout=` on the workerless path: a synchronous fetch
        cannot be preempted, but one that overran the budget still
        raises cleanly (with the fault event) instead of the timeout
        being silently ignored without workers."""
        if not self.timeout:
            return
        import time as _time

        elapsed = _time.perf_counter() - t0
        if elapsed > self.timeout:
            record_fault("data_worker_timeout",
                         f"single-process fetch of batch {batch} took "
                         f"{elapsed:.3f}s (timeout {self.timeout}s)")
            raise TimeoutError(
                f"DataLoader fetch of batch {batch} exceeded "
                f"timeout={self.timeout}s ({elapsed:.3f}s)")

    def _iter_single(self):
        import time as _time

        if self._iterable:
            batch = []
            n = 0
            t0 = _time.perf_counter()
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    out = self.collate_fn(batch)
                    self._check_timeout(t0, n)
                    yield out
                    batch = []
                    n += 1
                    t0 = _time.perf_counter()
            if batch and not self.drop_last:
                out = self.collate_fn(batch)
                self._check_timeout(t0, n)
                yield out
            return
        if self.batch_sampler is None:  # no auto-batching
            for i in range(len(self.dataset)):
                t0 = _time.perf_counter()
                item = self.dataset[i]
                self._check_timeout(t0, i)
                yield item
            return
        for n, indices in enumerate(self.batch_sampler):
            t0 = _time.perf_counter()
            fault_point("data.fetch", batch=n)
            batch = self._fetch(indices)
            self._check_timeout(t0, n)
            yield batch

    def _iter_workers(self):
        """Thread pool keeps `num_workers * prefetch_factor` batches staged."""
        task_q: queue.Queue = queue.Queue()
        out: dict = {}
        done = object()
        lock = threading.Lock()
        cond = threading.Condition(lock)
        n_tasks = 0
        for n_tasks, indices in enumerate(self.batch_sampler):
            task_q.put((n_tasks, indices))
        total = task_q.qsize()
        stop = threading.Event()
        max_ahead = self._window
        next_to_yield = [0]
        init_err = [None]

        def worker(wid):
            _worker_info.info = WorkerInfo(wid, self.num_workers, self.dataset)
            if self.worker_init_fn:
                try:
                    self.worker_init_fn(wid)
                except BaseException as e:
                    with cond:
                        init_err[0] = e
                        cond.notify_all()
                    return
            while not stop.is_set():
                try:
                    i, indices = task_q.get_nowait()
                except queue.Empty:
                    return
                with cond:
                    # plain wait, no poll: the consumer notify_all()s on
                    # every yield and on teardown, so a 20 Hz wakeup per
                    # idle worker bought nothing but scheduler noise
                    while i - next_to_yield[0] >= max_ahead and \
                            not stop.is_set():
                        cond.wait()
                if stop.is_set():
                    return
                try:
                    fault_point("data.worker_fetch", batch=i, worker=wid)
                    batch = (self._fetch_staged(indices)
                             if self.use_staging_pool
                             else self._fetch(indices))
                except BaseException as e:  # propagate to the consumer
                    batch = _WorkerError(e)
                with cond:
                    if stop.is_set():
                        # consumer already drained `out`; recycle rather
                        # than stage into the abandoned dict (slot leak)
                        if isinstance(batch, _Staged):
                            self._pool.release(batch.slot)
                        return
                    out[i] = batch
                    cond.notify_all()

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        import time as _time

        deadline = None
        try:
            for i in range(total):
                # the consumer-side queue wait: when workers can't keep
                # up, this span (not the collation itself) is where the
                # data-wait time lives on the timeline
                with _tracing.span("data_queue_wait", "io", batch=i), \
                        cond:
                    if self.timeout:
                        deadline = _time.time() + self.timeout
                    while i not in out:
                        if init_err[0] is not None:
                            raise init_err[0]
                        # producers notify_all() on every stored batch,
                        # so an untimed wait needs no poll; with a
                        # timeout, sleep exactly the remaining budget
                        if deadline is None:
                            cond.wait()
                            continue
                        remaining = deadline - _time.time()
                        if remaining > 0:
                            cond.wait(remaining)
                        if i not in out and _time.time() > deadline:
                            record_fault(
                                "data_worker_timeout",
                                f"batch {i} not produced within "
                                f"{self.timeout}s")
                            raise TimeoutError(
                                f"DataLoader worker timed out after "
                                f"{self.timeout}s waiting for batch {i}")
                    batch = out.pop(i)
                    next_to_yield[0] = i + 1
                    cond.notify_all()
                if isinstance(batch, _WorkerError):
                    raise batch.exc
                if isinstance(batch, _Staged):
                    batch = self._unstage(batch)
                yield batch
        finally:
            stop.set()  # set BEFORE taking cond: workers re-check under it
            with cond:
                # recycle slots of batches that were staged but never
                # yielded (early break) so the pool survives re-iteration
                for b in out.values():
                    if isinstance(b, _Staged):
                        self._pool.release(b.slot)
                out.clear()
                cond.notify_all()

    def __iter__(self):
        if self.num_workers > 0 and not self._iterable \
                and self.batch_sampler is not None:
            return self._iter_workers()
        return self._iter_single()
