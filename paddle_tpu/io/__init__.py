"""paddle.io (reference: python/paddle/io/__init__.py)."""
from .checkpoint import (  # noqa: F401
    CheckpointManager, abstract_state, load_checkpoint, save_checkpoint,
)
from .dataloader import DataLoader, default_collate_fn, get_worker_info  # noqa: F401
from .prefetch import DevicePrefetcher, prefetch_stats  # noqa: F401
from .dataset import (  # noqa: F401
    ChainDataset, ComposeDataset, ConcatDataset, Dataset, IterableDataset,
    Subset, TensorDataset, random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler, DistributedBatchSampler, RandomSampler, Sampler,
    SequenceSampler, SubsetRandomSampler, WeightedRandomSampler,
)
