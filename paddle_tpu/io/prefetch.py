"""Async input pipeline: double-buffered device staging (ROADMAP item 4).

`Model.fit` gained the `paddle_tpu_data_wait_seconds` histogram and
`data/data_wait` spans in PR 12 precisely so this module's win would be
measurable before it was built: until now the loader's `next()` ran
synchronously inside the step loop — host-side fetch/collate AND the
device commit serialized after step k-1's compute instead of hiding
under it. `DevicePrefetcher` is the record-now-execute-later principle
applied to input (the same bet trace fusion makes for ops): a
background thread pulls batches from any iterator, commits every leaf
to device memory (async `device_put` + a transfer barrier ON the
producer thread), and parks a bounded window of device-resident
batches — depth 2 = classic double buffering — so the consumer's
`next()` is a queue pop, not a pipeline.

Three tiers, composing:

* **Thread prefetch + device commit** (`DevicePrefetcher`): works over
  any batch iterator (a `DataLoader`, a generator, a list). H2D time is
  measured per batch into the ``paddle_tpu_h2d_seconds`` histogram and
  an ``io/h2d`` span from the SAME measurement (the PR-12
  reconciliation contract — `tracing.reconcile_with_metrics` holds the
  pair to exact agreement).
* **Staging-ring direct consume** (`staging_direct_ok`): the csrc/
  staging ring's slot views can feed `jax.device_put` directly — one
  copy, ring → device — behind an EXPLICIT per-backend opt-in
  (``PADDLE_TPU_STAGING_DIRECT=1``): the operator asserts
  `block_until_ready` truly barriers transfers on that backend (no
  cheap probe can — it returns early on the axon tunnel). A one-shot
  aliasing probe (device_put an aligned buffer, scribble on it, read
  the device value back) VETOES opt-ins on backends that zero-copy
  alias aligned host memory (XLA CPU). Default: today's `np.array`
  release barrier, which holds everywhere.
* **DP-sharded global assembly** (`sharding="dp"`): with a device mesh
  installed, each host loads only its `DistributedBatchSampler` rows
  and the commit step assembles the GLOBAL batch via
  `jax.make_array_from_process_local_data` — process-local data in, a
  NamedSharding-annotated global array out, so no host ever
  materializes (or transfers) the world-size-redundant global batch.

Degrade matrix (observable, never wedging — the PR-3 contract):

* producer thread dies without a word (crash, injected kill) →
  consumer notices via thread liveness, records a
  ``data_producer_died`` fault event, and degrades to synchronous
  pulls on its own thread (at most the one in-flight batch is lost);
* producer raises → the exception surfaces at the consumer's `next()`
  exactly as it would have synchronously;
* `timeout=` exceeded waiting on a stalled producer →
  ``data_worker_timeout`` fault event + `TimeoutError`;
* thread creation impossible / sharded assembly rejects a batch →
  synchronous / replicated fallback, counted in `prefetch_stats()`.

Import-weight contract: numpy + stdlib at import; jax only inside
methods (the io package must import on hosts without a backend).
"""
from __future__ import annotations

import os
import queue
import threading
import time
import weakref

import numpy as np

from ..runtime import telemetry as _telemetry
from ..runtime import tracing as _tracing
from ..runtime.resilience import fault_point, record_fault

__all__ = [
    "DevicePrefetcher", "prefetch_stats", "reset_prefetch_stats",
    "commit_arrays", "staging_direct_ok", "prefetch_enabled",
    "prefetch_depth", "note_h2d",
]

# fine buckets: H2D commits are sub-millisecond for small batches but
# the tail (global-batch assembly, first-touch allocation) matters
_H2D_BUCKETS = (1e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
                1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5)

_FALSY = ("0", "false", "no", "off")


def prefetch_enabled(default=True):
    """The `PADDLE_TPU_DATA_PREFETCH` switch (default ON: the parity
    gate — tools/data_smoke.py — holds the prefetch path loss-bit-exact
    vs synchronous consumption, so there is no correctness reason to
    leave the overlap on the table)."""
    raw = os.environ.get("PADDLE_TPU_DATA_PREFETCH", "").strip().lower()
    if not raw:
        return default
    return raw not in _FALSY


def prefetch_depth(default=2):
    """`PADDLE_TPU_DATA_PREFETCH_DEPTH` (default 2 — double buffering:
    one batch feeding step k, one committing for step k+1)."""
    try:
        return max(1, int(os.environ.get("PADDLE_TPU_DATA_PREFETCH_DEPTH",
                                         default)))
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# h2d measurement (histogram + span from the SAME numbers)

def note_h2d(seconds, wall_start, nbytes=0, kind="prefetch"):
    """One batch's host→device commit: `paddle_tpu_h2d_seconds`
    histogram + an ``io/h2d`` span emitted from the same measured
    duration, so the span sum and the histogram sum can never tell
    different stories (`tracing.reconcile_with_metrics` checks)."""
    try:
        _telemetry.histogram(
            "paddle_tpu_h2d_seconds",
            "per-batch host-to-device commit time (device_put + "
            "transfer barrier)", buckets=_H2D_BUCKETS).observe(seconds)
    except Exception:  # noqa: BLE001 — telemetry must never kill input
        pass
    _tracing.emit_span("h2d", "io", wall_start, seconds,
                       bytes=int(nbytes), kind=kind)


def commit_arrays(arrays, kind="step_inputs"):
    """Device-commit a list of host ndarrays (pass-through for values
    already on device), blocking until the transfer lands, with the
    h2d measurement. The serving engine stages its per-step ragged
    inputs through this so training and serving share ONE h2d lane."""
    import jax

    w0 = time.time()
    t0 = time.perf_counter()
    out, nbytes = [], 0
    for a in arrays:
        if isinstance(a, jax.Array):
            out.append(a)
        else:
            a = np.asarray(a)
            nbytes += a.nbytes
            out.append(jax.device_put(a))
    jax.block_until_ready(out)
    note_h2d(time.perf_counter() - t0, w0, nbytes, kind=kind)
    return out


# ---------------------------------------------------------------------------
# staging-ring direct consume: is device_put a real copy here?

_direct = [None]  # None = unprobed; probed once per process


def _device_put_aliases_host():
    """Probe whether `jax.device_put` of a 64-byte-aligned host buffer
    (exactly the shape of a staging-ring slot view) ALIASES the source
    instead of copying. XLA's CPU client zero-copies aligned numpy
    memory — on such a backend the staging slot must be host-copied
    before release or the ring would scribble over live device data."""
    import ctypes

    import jax

    try:
        raw = ctypes.create_string_buffer(256 + 64)
        addr = ctypes.addressof(raw)
        off = (-addr) % 64
        view = np.frombuffer(
            (ctypes.c_char * 256).from_address(addr + off),
            dtype=np.float32)
        view[:] = 1.0
        dev = jax.device_put(view)
        jax.block_until_ready(dev)
        view[:] = 2.0
        return bool(np.asarray(dev)[0] == 2.0)
    except Exception:  # noqa: BLE001 — unprobeable backend
        return True  # assume the worst: keep the copy release barrier


def staging_direct_ok():
    """True when the staging ring's slot views may feed `device_put`
    directly (one copy, ring → device) and be released after a
    `block_until_ready` barrier.

    EXPLICIT opt-in only (`PADDLE_TPU_STAGING_DIRECT=1`): the aliasing
    probe can prove `device_put` copies, but it cannot prove
    `block_until_ready` is a real transfer barrier — on the axon
    tunnel it is known to return early, and a 256-byte probe transfer
    completes before any scribble could catch that. So the operator
    asserts the barrier (per backend, validated on real hardware — the
    ROADMAP item-4 TPU tail), and the probe only VETOES an opt-in that
    would corrupt data outright (aliasing backends: the slot would be
    recycled under live device memory). Default, or =0: the `np.array`
    host-copy release barrier, which holds everywhere."""
    if _direct[0] is None:
        raw = os.environ.get("PADDLE_TPU_STAGING_DIRECT", "").strip().lower()
        want = bool(raw) and raw not in _FALSY
        _direct[0] = want and not _device_put_aliases_host()  # threadlint: ok[CL007] idempotent one-shot probe: a racing duplicate computes the same value
    return _direct[0]


# ---------------------------------------------------------------------------
# process-wide prefetcher accounting (profiler.summary + /statusz)

_stats_lock = threading.Lock()


def _zero_totals():
    return {
        "prefetchers": 0,     # DevicePrefetchers ever created
        "active": 0,          # currently open
        "depth": 0,           # most recent configured depth
        "batches": 0,         # batches delivered to consumers
        "stalls": 0,          # consumer waits > 1ms on an empty queue
        "stall_s": 0.0,       # total consumer wait
        "src_s": 0.0,         # producer time pulling from the source
        "h2d_s": 0.0,         # producer time committing to device
        "h2d_bytes": 0,
        "sharded_batches": 0,  # committed as global (NamedSharding) arrays
        "shard_fallbacks": 0,  # global assembly rejected → replicated put
        "producer_deaths": 0,  # silent producer death, degraded to sync
        "sync_fallbacks": 0,   # batches served by the degraded sync path
    }


_TOTALS = _zero_totals()


def prefetch_stats():
    """Process-wide prefetcher counters (depth, stalls, overlap ratio)
    — the `dispatch_stats()`-style snapshot `profiler.summary` and the
    /statusz route surface. ``overlap_ratio`` is the share of input-
    pipeline work (source pulls + device commits) hidden from the
    consumer: 1.0 = the step loop never waited, 0.0 = fully serial."""
    with _stats_lock:
        out = dict(_TOTALS)
    busy = out["src_s"] + out["h2d_s"]
    out["overlap_ratio"] = (max(0.0, min(1.0, 1.0 - out["stall_s"] / busy))
                            if busy > 0 else None)
    return out


def reset_prefetch_stats():
    with _stats_lock:
        _TOTALS.clear()
        _TOTALS.update(_zero_totals())


def _bump(**kv):
    with _stats_lock:
        for k, v in kv.items():
            _TOTALS[k] = _TOTALS.get(k, 0) + v


def _publish_gauges():
    """Mirror the aggregate into the metrics registry (dashboards); the
    authoritative numbers stay in `prefetch_stats()`."""
    try:
        st = prefetch_stats()
        _telemetry.gauge("paddle_tpu_prefetch_depth",
                         "configured device-prefetch depth").set(st["depth"])
        if st["overlap_ratio"] is not None:
            _telemetry.gauge(
                "paddle_tpu_prefetch_overlap_ratio",
                "share of input-pipeline work hidden from the step loop"
            ).set(st["overlap_ratio"])
    except Exception:  # noqa: BLE001
        pass


# ---------------------------------------------------------------------------
# the prefetcher

class _ProducerError:
    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


_DONE = object()          # producer exhausted the source cleanly
_STALL_EPS = 1e-3         # consumer waits above this count as stalls


class DevicePrefetcher:
    """Wrap `source` (any batch iterator/iterable) so a background
    thread keeps up to `depth` batches already committed to device.

    Batches flow through `jax.tree_util` — `Tensor` leaves (a
    registered pytree) have their payloads transfer-barriered, numpy
    leaves are `device_put` (or, with `sharding`, assembled into
    global arrays from process-local rows), and anything else —
    notably `LazyArray` fusion placeholders — passes through untouched
    so the producer thread can never force a fusion flush (the
    zero-new-flush-sites invariant tools/data_smoke.py gates).

    `sharding="dp"` (an axis name) enables the DP-mesh tier: leaves
    are committed with ``NamedSharding(mesh, P(axis, None, ...))`` via
    `jax.make_array_from_process_local_data`, so each host transfers
    only its shard. Pass `mesh=` explicitly or let it resolve from
    `distributed.env.get_mesh()`.

    Iterate it (`for batch in DevicePrefetcher(loader): ...`) and
    `close()` when abandoning it early; `with` works too.
    """

    def __init__(self, source, depth=None, timeout=None, sharding=None,
                 mesh=None, wrap_tensors=False):
        self.depth = max(1, int(depth) if depth is not None
                         else prefetch_depth())
        self.timeout = timeout
        self._src = iter(source)
        self._axis = sharding
        self._mesh = mesh
        # wrap committed leaves in Tensor (for sources that collate to
        # RAW numpy trees — the sharded fit path, where an eager Tensor
        # collate would commit locally only to be re-homed globally)
        self._wrap = bool(wrap_tensors)
        self._shardings = {}       # ndim -> NamedSharding (producer-only)
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._closed = False
        self._exhausted = False
        self._sync = False         # degraded: consumer pulls the source
        self.batches = 0
        if self._axis is not None and self._mesh is None:
            from ..distributed import env as _env

            self._mesh = _env.get_mesh()
            if self._mesh is None or \
                    self._axis not in self._mesh.axis_names:
                raise ValueError(
                    f"sharding axis {self._axis!r} needs an installed "
                    f"mesh carrying it (distributed.env.set_mesh)")
        _bump(prefetchers=1, active=1)
        with _stats_lock:
            _TOTALS["depth"] = self.depth
        # the thread holds a WEAK ref to this prefetcher (strong refs
        # only per-batch, dropped before the blocking put): a consumer
        # that abandons the iterator without close() lets GC collect
        # it, and the producer notices within one put cycle instead of
        # busy-waiting on the full queue forever
        self._thread = threading.Thread(
            target=_producer_loop,
            args=(weakref.ref(self), self._stop, self._q, self._src),
            name="paddle-tpu-prefetch", daemon=True)
        try:
            self._thread.start()
        except (RuntimeError, MemoryError) as e:  # can't spawn: stay sync
            self._sync = True
            self._thread = None
            record_fault("data_producer_died",
                         f"prefetch thread failed to start: {e}")
        _publish_gauges()

    # -- producer side (module-level loop: see the Thread note above) -------

    def _commit(self, batch):
        """Commit every host leaf of `batch` to device and barrier the
        transfers — on THIS thread, which is the whole point: the wait
        overlaps the consumer's compute."""
        import jax

        from ..core.fusion import LazyArray

        w0 = time.time()
        t0 = time.perf_counter()
        leaves, treedef = jax.tree_util.tree_flatten(batch)
        out, wait, nbytes, sharded = [], [], 0, False
        for x in leaves:
            if isinstance(x, jax.Array):
                target = (self._sharding_for(jax, x.ndim)
                          if self._mesh is not None else None)
                if target is not None and x.sharding != target:
                    # the collate step already committed this leaf to
                    # the LOCAL device (Tensor construction is eager
                    # jnp.asarray); the sharded tier re-homes it as a
                    # process-local shard of the GLOBAL array
                    a = np.asarray(x)
                    nbytes += a.nbytes
                    d, was_sharded = self._device_put(jax, a)
                    sharded = sharded or was_sharded
                    out.append(d)
                    wait.append(d)
                else:
                    out.append(x)
                    wait.append(x)
            elif type(x) is LazyArray:
                out.append(x)  # never force a fusion flush from here
            elif isinstance(x, (np.ndarray, np.generic)):
                a = np.asarray(x)
                nbytes += a.nbytes
                d, was_sharded = self._device_put(jax, a)
                sharded = sharded or was_sharded
                out.append(d)
                wait.append(d)
            else:
                out.append(x)
        if wait:
            jax.block_until_ready(wait)
        dt = time.perf_counter() - t0
        note_h2d(dt, w0, nbytes)
        _bump(h2d_s=dt, h2d_bytes=nbytes,
              **({"sharded_batches": 1} if sharded else {}))
        if self._wrap:
            from ..core.tensor import Tensor

            out = [Tensor(x) if isinstance(x, jax.Array) else x
                   for x in out]
        return jax.tree_util.tree_unflatten(treedef, out)

    def _device_put(self, jax, a):
        """One leaf to device: plain `device_put`, or — on the sharded
        tier — global-array assembly from this process's local rows.
        Returns (array, used_sharding)."""
        if self._mesh is None:
            return jax.device_put(a), False
        sh = self._sharding_for(jax, a.ndim)
        if sh is None:
            return jax.device_put(a), False
        try:
            return jax.make_array_from_process_local_data(sh, a), True
        except Exception:  # indivisible batch, API gap: replicate
            _bump(shard_fallbacks=1)
            return jax.device_put(a), False

    def _sharding_for(self, jax, ndim):
        sh = self._shardings.get(ndim)
        if sh is None:
            from jax.sharding import NamedSharding, PartitionSpec

            if ndim == 0:
                return None  # scalars replicate via plain device_put
            spec = PartitionSpec(self._axis, *([None] * (ndim - 1)))
            sh = self._shardings[ndim] = NamedSharding(self._mesh, spec)  # threadlint: ok[CL001] producer-thread-only memo (only _commit, which runs solely on the producer thread, reaches this); a racing duplicate would compute the identical value anyway
        return sh

    # -- consumer side -------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        if self._sync:
            return self._next_sync()
        deadline = (time.perf_counter() + self.timeout
                    if self.timeout else None)
        t0 = time.perf_counter()
        while True:
            try:
                item = self._q.get(timeout=0.05)
                break
            except queue.Empty:
                if self._thread is None or not self._thread.is_alive():
                    try:  # it may have enqueued right before exiting
                        item = self._q.get_nowait()
                        break
                    except queue.Empty:
                        pass
                    self._degrade("producer thread died")
                    return self._next_sync()
                if deadline is not None and time.perf_counter() > deadline:
                    record_fault(
                        "data_worker_timeout",
                        f"prefetcher waited {self.timeout}s for a batch")
                    raise TimeoutError(
                        f"DevicePrefetcher timed out after {self.timeout}s "
                        f"waiting for the producer")
        wait_dt = time.perf_counter() - t0
        _bump(stall_s=wait_dt,
              **({"stalls": 1} if wait_dt >= _STALL_EPS else {}))
        if wait_dt >= _STALL_EPS:
            try:
                _telemetry.counter(
                    "paddle_tpu_prefetch_stalls_total",
                    "consumer waits on an empty prefetch queue").inc()
            except Exception:  # noqa: BLE001
                pass
        if item is _DONE:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, _ProducerError):
            self._exhausted = True
            raise item.exc
        self.batches += 1
        _bump(batches=1)
        if self.batches % 16 == 1:
            _publish_gauges()
        return item

    def _degrade(self, why):
        """Silent producer death: fault event (postmortem-visible via
        the fault log / flight recorder) + synchronous fallback. The
        batch the producer was carrying is lost — a degrade, not a
        wedge, and the fault event says so."""
        self._sync = True  # threadlint: ok[CL001] consumer-thread-only flag (the producer that also reads it is dead by definition here)
        record_fault("data_producer_died",
                     f"{why}; degrading to synchronous input")
        _bump(producer_deaths=1)

    def _next_sync(self):
        _bump(sync_fallbacks=1)
        try:
            item = next(self._src)
        except StopIteration:
            self._exhausted = True
            raise
        self.batches += 1
        _bump(batches=1)
        return item

    # -- lifecycle -----------------------------------------------------------

    def stats(self):
        """This instance's view (process totals: `prefetch_stats`)."""
        return {"depth": self.depth, "batches": self.batches,
                "queued": self._q.qsize(), "sync": self._sync,
                "alive": bool(self._thread and self._thread.is_alive())}

    def close(self):
        """Stop the producer and drain staged batches. Idempotent;
        safe mid-iteration (early break / stop_training)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._drain()
        t = self._thread
        if t is not None and t.is_alive():
            # the producer exits on its next stop check; a source
            # blocked in a slow fetch finishes that item first (its
            # put aborts). Daemon thread: a pathological source can't
            # hold the step loop hostage past this bounded join.
            t.join(timeout=5.0)
        self._drain()
        _bump(active=-1)

    def _drain(self):
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                return

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _producer_loop(ref, stop, q, src):
    """The prefetch thread body. `ref` is a weakref to the owning
    DevicePrefetcher: a strong ref is taken per batch (to run
    `_commit`) and DROPPED before the blocking put, so an abandoned
    prefetcher (consumer gone, no close()) is collectable — the loop
    then exits within one put cycle instead of leaking a thread that
    pins `depth` device-resident batches forever."""
    n = 0
    while not stop.is_set():
        pf = ref()
        if pf is None:
            return
        try:
            # OUTSIDE the error capture on purpose: an injected raise
            # here kills the producer without a sentinel — the
            # deterministic stand-in for an abrupt thread death the
            # consumer must survive on its own
            fault_point("prefetch.producer", batch=n)
        except BaseException:  # noqa: BLE001
            return
        t0 = time.perf_counter()
        try:
            item = next(src)
        except StopIteration:
            item = _DONE
        except BaseException as e:  # surfaces at the consumer
            item = _ProducerError(e)
        src_dt = time.perf_counter() - t0
        if not isinstance(item, _ProducerError) and item is not _DONE:
            try:
                item = pf._commit(item)
            except BaseException as e:
                item = _ProducerError(e)
            _bump(src_s=src_dt)
        pf = None  # noqa: F841 — drop the strong ref before blocking
        if not _producer_put(ref, stop, q, item):
            return  # closing/abandoned: the in-flight batch is dropped
        if isinstance(item, _ProducerError) or item is _DONE:
            return
        n += 1


def _producer_put(ref, stop, q, item):
    """Bounded put that aborts when the prefetcher closes OR was
    garbage-collected (no consumer will ever drain the queue)."""
    while not stop.is_set():
        if ref() is None:
            return False
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False
