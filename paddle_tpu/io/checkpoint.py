"""Sharded, async checkpointing (orbax-backed).

Reference capability: python/paddle/distributed/fleet/utils/fs.py +
fleet checkpoint saving and paddle.save on sharded state
(python/paddle/framework/io.py). TPU-native design: checkpoints are orbax
PyTree checkpoints — each jax.Array leaf is written per-shard (OCDBT), so a
dp/tp/pp-sharded train state saves and restores without gathering to one
host; `async_save` overlaps serialization with the next train steps.
Restore takes an abstract target (jax.eval_shape-style) carrying
NamedShardings, so arrays come back resident on the right devices.

Layout matches distributed/elastic.py's `latest_checkpoint`: one numbered
subdirectory per step under the root.
"""
from __future__ import annotations

import os

import jax
import numpy as np

from ..core.tensor import Tensor

__all__ = ["CheckpointManager", "save_checkpoint", "load_checkpoint",
           "abstract_state"]


def _unwrap(tree):
    return jax.tree_util.tree_map(
        lambda x: x._value if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def abstract_state(tree, mesh=None, spec_fn=None):
    """Build the abstract restore target: ShapeDtypeStructs carrying each
    leaf's sharding (or one derived from spec_fn(path_leaf) on `mesh`)."""
    from jax.sharding import NamedSharding

    def to_abstract(x):
        if isinstance(x, Tensor):
            x = x._value
        if isinstance(x, jax.Array):
            sharding = x.sharding
            if mesh is not None and spec_fn is not None:
                sharding = NamedSharding(mesh, spec_fn(x))
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
        x = np.asarray(x)
        return jax.ShapeDtypeStruct(x.shape, x.dtype)

    return jax.tree_util.tree_map(to_abstract, tree,
                                  is_leaf=lambda x: isinstance(x, Tensor))


class CheckpointManager:
    """Step-numbered async sharded checkpoints with retention.

    Usage:
        mngr = CheckpointManager(dir, max_to_keep=3)
        mngr.save(step, {"params": params, "opt": opt_state})   # async
        state = mngr.restore(target=abstract_state(live_state))
    """

    def __init__(self, directory, max_to_keep=5, async_save=True,
                 save_interval_steps=1):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=async_save,
            ))

    def save(self, step, state, force=False):
        """Queue an async sharded save of `state` (pytree of Tensors/arrays).
        Returns True if the save was accepted (interval/retention policy)."""
        return self._mngr.save(
            int(step), args=self._ocp.args.StandardSave(_unwrap(state)),
            force=force)

    def restore(self, step=None, target=None):
        """Restore `step` (newest if None). With `target` (from
        abstract_state), leaves restore sharded onto their devices."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no complete checkpoint under {self.directory}")
        args = (self._ocp.args.StandardRestore(target)
                if target is not None else None)
        return self._mngr.restore(int(step), args=args)

    def latest_step(self):
        return self._mngr.latest_step()

    def all_steps(self):
        return sorted(self._mngr.all_steps())

    def wait(self):
        """Block until queued async saves are durable on disk."""
        self._mngr.wait_until_finished()

    def close(self):
        self._mngr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def save_checkpoint(directory, step, state, async_save=False):
    """One-shot sharded save of `state` at `step` under `directory`."""
    with CheckpointManager(directory, max_to_keep=None,
                           async_save=async_save) as m:
        m.save(step, state, force=True)
        m.wait()


def load_checkpoint(directory, step=None, target=None):
    """One-shot restore (newest step if None)."""
    with CheckpointManager(directory) as m:
        return m.restore(step, target=target)
