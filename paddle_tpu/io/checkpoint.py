"""Sharded, async checkpointing (orbax-backed) with integrity
verification, bounded retry, and restore fallback.

Reference capability: python/paddle/distributed/fleet/utils/fs.py +
fleet checkpoint saving and paddle.save on sharded state
(python/paddle/framework/io.py). TPU-native design: checkpoints are orbax
PyTree checkpoints — each jax.Array leaf is written per-shard (OCDBT), so a
dp/tp/pp-sharded train state saves and restores without gathering to one
host; `async_save` overlaps serialization with the next train steps.
Restore takes an abstract target (jax.eval_shape-style) carrying
NamedShardings, so arrays come back resident on the right devices.

Resilience contract (runtime/resilience.py):

* `save`/`restore` wrap their orbax calls in bounded retry with
  exponential backoff + jitter on transient I/O errors (`save_retries`
  / `restore_retries` fault events).
* A failed save — sync after retries, or an async save whose error
  surfaces later in `wait()` — degrades to a warning + `save_failures`
  fault event and returns False. It never kills training: the previous
  complete checkpoint is still on disk, which is the whole point of
  taking checkpoints.
* At commit, a per-leaf checksum manifest (`integrity.json`, crc32 +
  shape + dtype per leaf path) is written atomically into the step
  directory. Async saves get their manifest flushed as soon as the
  step directory is committed (next save / wait / latest_step /
  close) — a process killed mid-async-save leaves only an orbax tmp
  dir, which every reader here ignores.
* `restore` verifies restored leaves against the manifest and, on
  corruption (checksum mismatch OR an unreadable/torn shard), falls
  back to the previous complete step automatically (`restore_fallbacks`
  fault event), raising only when no complete step survives.

Layout matches distributed/elastic.py's `latest_checkpoint`: one numbered
subdirectory per step under the root — and both sides now share ONE
definition of "complete" (`latest_complete_step`): a bare-digit
directory (orbax commits by atomic rename), which excludes in-flight
`<step>.orbax-checkpoint-tmp-*` dirs by construction.
"""
from __future__ import annotations

import json
import os
import time
import warnings
import zlib

import jax
import numpy as np

from ..core.tensor import Tensor
from ..runtime import telemetry as _telemetry
from ..runtime import tracing as _tracing
from ..runtime.resilience import (
    IntegrityError, fault_point, record_fault, retry_with_backoff,
    atomic_write_json,
)

__all__ = ["CheckpointManager", "save_checkpoint", "load_checkpoint",
           "abstract_state", "leaf_checksums", "verify_checksums",
           "complete_steps", "latest_complete_step", "IntegrityError",
           "INTEGRITY_BASENAME", "publish_complete_steps",
           "latest_common_complete_step"]

INTEGRITY_BASENAME = "integrity.json"


def _unwrap(tree):
    return jax.tree_util.tree_map(
        lambda x: x._value if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def abstract_state(tree, mesh=None, spec_fn=None):
    """Build the abstract restore target: ShapeDtypeStructs carrying each
    leaf's sharding (or one derived from spec_fn(path_leaf) on `mesh`)."""
    from jax.sharding import NamedSharding

    def to_abstract(x):
        if isinstance(x, Tensor):
            x = x._value
        if isinstance(x, jax.Array):
            sharding = x.sharding
            if mesh is not None and spec_fn is not None:
                sharding = NamedSharding(mesh, spec_fn(x))
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
        x = np.asarray(x)
        return jax.ShapeDtypeStruct(x.shape, x.dtype)

    return jax.tree_util.tree_map(to_abstract, tree,
                                  is_leaf=lambda x: isinstance(x, Tensor))


# ---------------------------------------------------------------------------
# one shared definition of "complete step" (elastic resume + retention
# + restore fallback all read this — they can never disagree again)

def complete_steps(directory):
    """Sorted complete (committed) checkpoint steps under `directory`.

    Matches orbax's own commit semantics: a step is committed by
    atomically renaming `<step>.orbax-checkpoint-tmp-<ts>` to
    `<step>`, so a BARE-DIGIT directory is durably complete and an
    in-flight/torn save never parses as one (its name carries the tmp
    suffix). The old elastic scan keyed on a hand-rolled `.incomplete`
    marker that orbax never writes — a torn async save looked complete
    to resume while retention/restore disagreed."""
    if not os.path.isdir(directory):
        return []
    return sorted(int(name) for name in os.listdir(directory)
                  if name.isdigit()
                  and os.path.isdir(os.path.join(directory, name)))


def latest_complete_step(directory):
    """Newest complete checkpoint step under `directory`, or None."""
    steps = complete_steps(directory)
    return steps[-1] if steps else None


# ---------------------------------------------------------------------------
# coordinated restore (multihost): the cluster-wide definition of
# "newest step EVERYONE completed", over a coordination store

_CKPT_PREFIX = "ckpt"  # mirrors distributed/coordination.py CKPT_PREFIX
#                        (duck-typed store param keeps this module free
#                        of a distributed/ import)


def publish_complete_steps(store, rank, directory):
    """Publish this rank's complete checkpoint steps into the
    coordination store (``ckpt/rank_<r>``). Ranks publish at every save
    commit and again at restore time; `latest_common_complete_step`
    intersects the publications so no rank ever restores a step a peer
    never committed. Returns the published step list."""
    steps = complete_steps(directory)
    store.put(f"{_CKPT_PREFIX}/rank_{int(rank)}",
              {"rank": int(rank), "steps": steps, "wall": time.time()})
    return steps


def latest_common_complete_step(store, expected_ranks=None, timeout=30.0,
                                poll=0.05, min_wall=None, world_size=None):
    """The max step EVERY publishing rank has complete — the one step a
    crashed multihost job can restore WITHOUT diverging when rank k
    died mid-async-save (k's torn step never entered k's publication,
    so the intersection excludes it).

    With `expected_ranks` (an int) the scan waits up to `timeout`
    seconds for that many rank publications before intersecting; a
    publication that never arrives degrades — `rendezvous_timeouts`
    fault event, intersect what IS present — rather than hanging the
    restore. With `min_wall`, only publications at least that fresh
    count toward the wait (each restarting rank republishes, and the
    per-rank key makes a republication REPLACE the stale one — so
    after the wait, live ranks are fresh and only genuinely-dead
    ranks' records are stale). The final intersection always uses
    every record present: a dead rank's stale list is exactly the
    conservative input the protocol wants. Without `min_wall`, a
    previous run's leftover publications can satisfy the wait before
    live ranks republish — pass your own publication time minus an
    NTP-skew allowance. Returns None when no step is common (fresh
    start).
    A stale publication from a dead rank stays safe by construction:
    its step list is exactly what that rank had committed, so the
    intersection only ever shrinks toward older, safer steps.

    Retention interacts with the intersection: survivors that run far
    past a dead rank eventually prune (`max_to_keep`) the steps the
    dead rank still holds, and the intersection goes EMPTY — a
    consistent outcome (every rank computes the same None) but a total
    restart. Size `max_to_keep * save_interval` to cover the longest
    peer outage the job should survive."""
    if world_size is None:
        world_size = expected_ranks
    deadline = time.monotonic() + float(timeout)
    while True:
        records = [store.get(k) for k in store.list(_CKPT_PREFIX)]
        records = [r for r in records
                   if isinstance(r, dict) and "steps" in r
                   # a store dir reused by a SMALLER world holds ghost
                   # publications whose frozen lists would poison every
                   # future intersection (same ghost-record class the
                   # quorum monitor filters from down/)
                   and (world_size is None
                        or 0 <= int(r.get("rank", -1)) < int(world_size))]
        fresh = records if min_wall is None else [
            r for r in records if float(r.get("wall", 0.0)) >= min_wall]
        if expected_ranks is None or len(fresh) >= int(expected_ranks):
            break
        if time.monotonic() >= deadline:
            record_fault(
                "rendezvous_timeouts",
                f"complete-step publications: {len(fresh)}/"
                f"{expected_ranks} fresh ranks within {timeout}s")
            break
        time.sleep(min(poll, max(0.0, deadline - time.monotonic())))
    if not records:
        return None
    common = set(int(s) for s in records[0]["steps"])
    for r in records[1:]:
        common &= set(int(s) for s in r["steps"])
    return max(common) if common else None


# ---------------------------------------------------------------------------
# per-leaf integrity manifest

def _leaf_items(tree):
    """[(path_str, np_array)] over array-like leaves, orbax-key style."""
    leaves, _ = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=lambda x: isinstance(x, Tensor))
    out = []
    for path, leaf in leaves:
        if isinstance(leaf, Tensor):
            leaf = leaf._value
        if leaf is None:
            continue
        out.append((jax.tree_util.keystr(path),
                    np.ascontiguousarray(np.asarray(leaf))))
    return out


def leaf_checksums(tree):
    """{leaf path -> {crc32, shape, dtype}} over the LOGICAL value of
    each array leaf (sharded arrays checksum their full contents, so a
    restore onto a different sharding still verifies)."""
    out = {}
    for path, arr in _leaf_items(tree):
        out[path] = {"crc32": zlib.crc32(arr.tobytes()),
                     "shape": list(arr.shape), "dtype": str(arr.dtype)}
    return out


def verify_checksums(tree, manifest):
    """Leaf paths present in BOTH `tree` and `manifest` whose checksum,
    shape or dtype disagree (empty list = verified). Paths only on one
    side are skipped — partial restores verify their intersection."""
    bad = []
    for path, arr in _leaf_items(tree):
        want = manifest.get(path)
        if want is None:
            continue
        if (list(arr.shape) != list(want["shape"])
                or str(arr.dtype) != want["dtype"]
                or zlib.crc32(arr.tobytes()) != want["crc32"]):
            bad.append(path)
    return bad


class CheckpointManager:
    """Step-numbered async sharded checkpoints with retention, retry,
    integrity manifests, and restore fallback.

    Usage:
        mngr = CheckpointManager(dir, max_to_keep=3)
        mngr.save(step, {"params": params, "opt": opt_state})   # async
        state = mngr.restore(target=abstract_state(live_state))
    """

    def __init__(self, directory, max_to_keep=5, async_save=True,
                 save_interval_steps=1, verify_integrity=True,
                 retry_attempts=4):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self.verify_integrity = bool(verify_integrity)
        self.retry_attempts = max(1, int(retry_attempts))
        os.makedirs(self.directory, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=async_save,
            ))
        # step -> checksum manifest, computed at save() time and written
        # into the step dir as soon as orbax commits it (async saves
        # commit after save() returns)
        self._pending_manifests = {}
        self.last_restored_step = None

    # -- integrity manifests -----------------------------------------------
    def _step_dir(self, step):
        return os.path.join(self.directory, str(int(step)))

    def _manifest_path(self, step):
        return os.path.join(self._step_dir(step), INTEGRITY_BASENAME)

    def _flush_manifests(self):
        """Write pending checksum manifests for every step orbax has
        committed since; drop entries for steps that died (tmp dir of a
        killed save) or were pruned by retention."""
        if not self._pending_manifests:
            return
        committed = set(complete_steps(self.directory))
        for step in list(self._pending_manifests):
            if step in committed:
                manifest = self._pending_manifests.pop(step)
                try:
                    fault_point("checkpoint.manifest_write", step=step,
                                path=self._step_dir(step))
                    atomic_write_json(self._manifest_path(step),
                                      {"version": 1, "leaves": manifest})
                except OSError as e:
                    # manifest is advisory: restore treats a missing one
                    # as complete-but-unverified rather than incomplete
                    record_fault("save_failures",
                                 f"manifest write step {step}: {e}")
                    warnings.warn(
                        f"paddle_tpu checkpoint: could not write integrity "
                        f"manifest for step {step}: {e}", stacklevel=3)
            elif not os.path.exists(self._step_dir(step)) and not any(
                    n.startswith(f"{step}.") for n in (
                        os.listdir(self.directory)
                        if os.path.isdir(self.directory) else [])):
                self._pending_manifests.pop(step, None)

    def _read_manifest(self, step):
        try:
            with open(self._manifest_path(step)) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return None
        return data.get("leaves") if isinstance(data, dict) else None

    # -- save ---------------------------------------------------------------
    def save(self, step, state, force=False):
        """Queue an async sharded save of `state` (pytree of Tensors/
        arrays). Transient I/O errors retry with backoff; a save that
        still fails (or an earlier async save whose error surfaces now)
        degrades to a warning + `save_failures` fault event and returns
        False — it never raises into the training loop."""
        step = int(step)
        state = _unwrap(state)
        self._flush_manifests()
        manifest = leaf_checksums(state) if self.verify_integrity else None
        t0 = time.perf_counter()

        def _do_save():
            fault_point("checkpoint.save", step=step,
                        directory=self.directory)
            return self._mngr.save(
                step, args=self._ocp.args.StandardSave(state), force=force)

        try:
            accepted = retry_with_backoff(
                _do_save, attempts=self.retry_attempts,
                retry_on=(OSError,), counter="save_retries",
                describe=f"checkpoint save step {step}")
        except Exception as e:  # noqa: BLE001 — degrade, never kill training
            record_fault("save_failures",
                         f"step {step}: {type(e).__name__}: {e}")
            self._note_save(step, time.perf_counter() - t0, accepted=False)
            warnings.warn(
                f"paddle_tpu checkpoint: save of step {step} failed after "
                f"{self.retry_attempts} attempts ({type(e).__name__}: {e}) "
                "— training continues from the previous checkpoint",
                stacklevel=2)
            return False
        self._note_save(step, time.perf_counter() - t0, accepted=accepted)
        if accepted and manifest is not None:
            self._pending_manifests[step] = manifest
        # the kill-mid-async-save injection site: at this point the save
        # is queued/in-flight but (for async managers) not yet committed
        fault_point("checkpoint.async_started", step=step,
                    directory=self.directory)
        return accepted

    def _note_save(self, step, seconds, accepted):
        """Telemetry: one save attempt's duration (enqueue time for an
        async manager — the commit happens in the background; wait()
        durations bound the rest) as a structured event + histogram.
        Guarded: a telemetry error (registration clash) must never be
        mistaken for — or turn into — a checkpoint failure."""
        try:
            _telemetry.emit("checkpoint_save", step=step,
                            seconds=round(seconds, 6),
                            accepted=bool(accepted))
            _telemetry.histogram(
                "paddle_tpu_checkpoint_save_seconds",
                "checkpoint save call duration (enqueue, for async saves)"
            ).observe(seconds)
            # span from the SAME measured duration as the histogram
            # observation — the reconciliation contract
            _tracing.emit_span("save", "checkpoint", time.time() - seconds,
                               seconds, step=step, accepted=bool(accepted))
        except Exception:  # noqa: BLE001
            pass

    @staticmethod
    def _note_restore(step, seconds, fell_back):
        """Telemetry for a SUCCESSFUL restore. Guarded — and called
        outside the per-step fallback try-block: an exception here
        would otherwise convict the good restore it is reporting and
        fall back to an older checkpoint."""
        try:
            _telemetry.emit("checkpoint_restore", step=step,
                            seconds=round(seconds, 6), fell_back=fell_back)
            _telemetry.histogram(
                "paddle_tpu_checkpoint_restore_seconds",
                "checkpoint restore duration (incl. fallbacks)"
            ).observe(seconds)
            _tracing.emit_span("restore", "checkpoint",
                               time.time() - seconds, seconds, step=step,
                               fell_back=fell_back)
        except Exception:  # noqa: BLE001
            pass

    # -- restore ------------------------------------------------------------
    def restore(self, step=None, target=None, strict=False):
        """Restore `step` (newest complete if None). With `target` (from
        abstract_state), leaves restore sharded onto their devices.

        Integrity: if the step carries a checksum manifest, restored
        leaves are verified against it. On verification failure or an
        unreadable step, restore falls back to the previous complete
        step (fault event `restore_fallbacks`) unless `strict=True`.
        Raises FileNotFoundError when no complete step restores."""
        self.wait()  # surface async errors + flush manifests first
        steps = complete_steps(self.directory)
        if step is not None:
            steps = [s for s in steps if s <= int(step)]
            if not steps or steps[-1] != int(step):
                raise FileNotFoundError(
                    f"no complete checkpoint for step {step} under "
                    f"{self.directory}")
        if not steps:
            raise FileNotFoundError(
                f"no complete checkpoint under {self.directory}")
        # explicit StandardRestore even with no target: a manager that
        # never saved in this process has no handler registry to infer
        # the item type from (target=None restores as saved, host np)
        args = self._ocp.args.StandardRestore(target)
        first_error = None
        t0 = time.perf_counter()
        for s in reversed(steps):
            try:
                restored = retry_with_backoff(
                    lambda s=s: self._restore_once(s, args),
                    attempts=self.retry_attempts,
                    retry_on=(OSError, TimeoutError),
                    counter="restore_retries",
                    describe=f"checkpoint restore step {s}")
                self.last_restored_step = s
                self._note_restore(s, time.perf_counter() - t0,
                                   fell_back=s != steps[-1])
                return restored
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:  # noqa: BLE001 — corrupt/torn step
                if first_error is None:
                    first_error = e
                if strict:
                    raise
                record_fault("restore_fallbacks",
                             f"step {s}: {type(e).__name__}: {e}")
                warnings.warn(
                    f"paddle_tpu checkpoint: restore of step {s} failed "
                    f"({type(e).__name__}: {e}) — falling back to the "
                    "previous complete step", stacklevel=2)
        raise FileNotFoundError(
            f"no restorable checkpoint under {self.directory} "
            f"(first failure: {first_error})")

    def _restore_once(self, step, args):
        fault_point("checkpoint.restore", step=step,
                    directory=self.directory)
        restored = self._mngr.restore(int(step), args=args)
        if self.verify_integrity:
            manifest = self._read_manifest(step)
            if manifest:
                bad = verify_checksums(restored, manifest)
                if bad:
                    raise IntegrityError(
                        f"step {step}: checksum mismatch on "
                        f"{len(bad)} leaves ({', '.join(bad[:3])}"
                        f"{', ...' if len(bad) > 3 else ''})")
        return restored

    # -- introspection ------------------------------------------------------
    def latest_step(self):
        """Newest COMPLETE step (tmp-dir aware; shared with elastic)."""
        self._flush_manifests()
        return latest_complete_step(self.directory)

    def publish_complete(self, store, rank):
        """Flush pending integrity manifests, then publish this rank's
        complete steps into a coordination store (the multihost
        coordinated-restore protocol). Returns the published list."""
        self._flush_manifests()
        return publish_complete_steps(store, rank, self.directory)

    def discard_after(self, step):
        """Delete every complete step NEWER than `step` — the
        coordinated-restart truncation: once the cluster agreed to
        resume from `step`, any step a rank holds past it encodes a
        future the cluster abandoned. Keeping those steps would (a)
        make later interval saves collide with them (orbax never
        overwrites an existing step) and (b) leave BadStepGuard's
        "newest complete" pointing at divergent state. Returns the
        steps removed."""
        removed = []
        with _tracing.span("discard_after", "checkpoint", after=int(step)):
            for s in complete_steps(self.directory):
                if s <= int(step):
                    continue
                try:
                    self._mngr.delete(s)  # orbax keeps its bookkeeping
                except Exception:  # noqa: BLE001 — fall back to the fs
                    import shutil

                    shutil.rmtree(self._step_dir(s), ignore_errors=True)
                self._pending_manifests.pop(s, None)
                removed.append(s)
        if removed:
            _telemetry.emit("checkpoint_discard", after=int(step),
                            steps=removed)
        return removed

    def all_steps(self):
        return sorted(self._mngr.all_steps())

    def wait(self):
        """Block until queued async saves are durable on disk. An async
        save that failed surfaces here: warning + fault event, not an
        exception (the run survives; the previous checkpoint stands).
        Span-traced ("checkpoint/async_wait"): the async-commit stall
        is exactly the kind of step-time sink the timeline exists to
        expose."""
        with _tracing.span("async_wait", "checkpoint"):
            try:
                self._mngr.wait_until_finished()
            except Exception as e:  # noqa: BLE001 — degrade, never kill
                record_fault("save_failures",
                             f"async save: {type(e).__name__}: {e}")
                warnings.warn(
                    f"paddle_tpu checkpoint: async save failed "
                    f"({type(e).__name__}: {e}) — training continues from "
                    "the previous checkpoint", stacklevel=2)
            self._flush_manifests()

    def close(self):
        self.wait()
        try:
            self._mngr.close()
        except Exception as e:  # noqa: BLE001 — close surfaces async errors
            record_fault("save_failures",
                         f"close: {type(e).__name__}: {e}")
            warnings.warn(f"paddle_tpu checkpoint: close failed "
                          f"({type(e).__name__}: {e})", stacklevel=2)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def save_checkpoint(directory, step, state, async_save=False,
                    verify_integrity=True):
    """One-shot sharded save of `state` at `step` under `directory`."""
    with CheckpointManager(directory, max_to_keep=None,
                           async_save=async_save,
                           verify_integrity=verify_integrity) as m:
        m.save(step, state, force=True)
        m.wait()


def load_checkpoint(directory, step=None, target=None, strict=False):
    """One-shot restore (newest complete step if None)."""
    with CheckpointManager(directory) as m:
        return m.restore(step, target=target, strict=strict)
