"""AMP (reference: python/paddle/amp + fluid/dygraph/amp).

TPU-native: bf16 is the native mixed-precision dtype (MXU computes bf16×bf16
→f32); requests for float16 map to bfloat16 by default (fp16 is emulated on
TPU). Dynamic loss scaling is kept for API parity — with bf16 it is
mathematically inert (same exponent range as f32) but harmless.

auto_cast works by op-name interception in the eager dispatcher
(core.autograd.apply consults _amp_state): white-list ops run in the low
dtype, black-list ops in f32 — the same two-list design as the reference's
fluid/dygraph/amp/auto_cast.py.

Dispatch-cache interplay: apply() runs this cast BEFORE handing the op to
the jit-cached dispatcher (core/dispatch.py), so the cast result is part
of the cached program key via the post-cast input avals — a white-list op
under AMP keys on bf16 avals and can never collide with its f32 entry,
and an op whose inputs already carry the target dtype shares its entry
with the AMP-off case because the emitted program is identical. The same
holds for the backward pullback cache: residuals are recorded post-cast,
so recompute inside the cached vjp matches the forward's dtypes exactly.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor
from ..core import autograd as _ag

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate",
           "WHITE_LIST", "BLACK_LIST"]

WHITE_LIST = {
    "matmul", "mm", "bmm", "conv1d", "conv2d", "conv3d", "linear", "einsum",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
}
# ops that must stay f32 for numerics
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "logsumexp", "softmax",
    "log_softmax", "cross_entropy", "mean", "sum", "pow", "square",
    "reciprocal", "rsqrt", "norm", "cosh", "sinh",
}
# ops AMP must never touch: in-place value writes keep the target's dtype
EXEMPT_LIST = {"set_value"}


class _AmpState:
    enabled = False
    level = "O1"
    dtype = jnp.bfloat16
    custom_white = set()
    custom_black = set()


_state = _AmpState()


def _amp_active():
    return _state.enabled


def _amp_cast_args(fn_name, vals):
    """Called from core.autograd.apply: cast float32 arrays per AMP policy."""
    if fn_name in EXEMPT_LIST:
        return vals
    low = _state.dtype
    in_white = fn_name in WHITE_LIST or fn_name in _state.custom_white
    in_black = fn_name in BLACK_LIST or fn_name in _state.custom_black
    if _state.level == "O2":
        target = jnp.float32 if in_black else low
    else:
        if in_black:  # black wins (custom black overrides default white)
            target = jnp.float32
        elif in_white:
            target = low
        else:
            return vals
    out = []
    for v in vals:
        if hasattr(v, "dtype") and v.dtype in (jnp.float32, jnp.bfloat16,
                                               jnp.float16) \
                and v.dtype != target:
            out.append(v.astype(target))
        else:
            out.append(v)
    return out


_ag._amp_hook = (_amp_active, _amp_cast_args)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="float16", use_promote=True):
    """paddle.amp.auto_cast. dtype float16 maps to bfloat16 on TPU."""
    name = dtypes.convert_dtype(dtype)
    low = jnp.bfloat16 if name in ("float16", "bfloat16") else jnp.float16
    prev = (_state.enabled, _state.level, _state.dtype, _state.custom_white,
            _state.custom_black)
    _state.enabled = bool(enable)
    _state.level = level
    _state.dtype = low
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.level, _state.dtype, _state.custom_white,
         _state.custom_black) = prev


amp_guard = auto_cast


def _is_norm_layer(layer):
    from ..nn.layer import norm as _norm

    # SpectralNorm included: its power-iteration buffers (weight_u/v)
    # need f32 — bf16 iteration degrades the sigma estimate
    return isinstance(layer, (_norm._BatchNormBase, _norm.LayerNorm,
                              _norm.GroupNorm, _norm._InstanceNormBase,
                              _norm.SpectralNorm))


def decorate(models, optimizers=None, level="O2", dtype="float16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate: cast model params to the AMP dtype (O2).

    Norm layers (BatchNorm*/SyncBatchNorm/LayerNorm/GroupNorm/InstanceNorm*)
    and their buffers stay float32 — bf16 running-stat accumulation
    (momentum ~0.9 of small deltas) loses precision; the reference's
    amp_decorate keeps them f32 for the same reason.
    """
    target = "bfloat16" if dtypes.convert_dtype(dtype) in (
        "float16", "bfloat16") else dtype
    single = not isinstance(models, (list, tuple))
    ms = [models] if single else list(models)
    if level == "O2":
        jd = dtypes.to_jax_dtype(target)
        for mdl in ms:
            for layer in mdl.sublayers(include_self=True):
                if not _is_norm_layer(layer):
                    layer._cast_to(jd, include_sublayers=False)
    if optimizers is None:
        return models
    # master_weight routes to the optimizer's multi_precision mechanism
    # (f32 master + f32 states for half params): None keeps the
    # optimizer's own AUTO default; True/False force it (reference:
    # python/paddle/amp/auto_cast.py amp_decorate master_weight)
    if master_weight is not None:
        opts = (optimizers if isinstance(optimizers, (list, tuple))
                else [optimizers])
        for opt in opts:
            opt._multi_precision = bool(master_weight)
    return models, optimizers


# module-level pure ops for the scaler's lazy routes: fusion.record
# keys on the code object, so these must be stable defs (a lambda per
# call would defeat the trace-fingerprint cache)
def _notfinite_op(g):
    return jnp.any(~jnp.isfinite(g))


def _or_op(a, b):
    return a | b


class GradScaler:
    """Dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py)."""

    def __init__(self, enable=True, init_loss_scaling=65536.0,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        from ..core import fusion as _fusion

        inv = 1.0 / self._scale
        bad = None  # device-side flag; ONE host sync at the end
        for p in optimizer._param_list:
            if p._grad is not None:
                # lazy routes: under trace fusion the unscale and the
                # finite probe RECORD into the pending trace (a raw
                # jnp call on a deferred grad would materialize it via
                # __jax_array__, flushing the fused fwd+bwd mid-step —
                # fuselint FL006); with fusion off these are plain
                # eager calls on concrete arrays, bit-identical to the
                # raw expressions they replace
                g = _fusion.lazy_mul(p._grad._value, inv)
                p._grad._value = g
                nf = _fusion.lazy_apply(_notfinite_op, g)
                bad = nf if bad is None else _fusion.lazy_apply(
                    _or_op, bad, nf)
        # the ONE intentional host sync of the unscale: everything
        # above stays in the fused program up to this read
        self._found_inf = bool(bad) if bad is not None else False  # fuselint: ok[FL002] the scaler's single reviewed sync point
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not getattr(self, "_unscaled", False):
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._unscaled = False

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def minimize(self, optimizer, scaled_loss):
        # reference pattern: scaled.backward() already ran; minimize only
        # unscales + steps + updates the scale
        self.step(optimizer)
        self.update()
        optimizer.clear_grad()

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every,
                "decr_every_n_nan_or_inf": self._decr_every,
                "good_steps": self._good_steps, "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)
