"""paddle.signal — STFT family (reference: python/paddle/signal.py).

TPU-native design: frames are gathered with a static index grid (one XLA
gather, MXU-friendly batched FFT over the frame axis); overlap-add is a
single scatter-add. Everything is shape-static so the whole pipeline fuses
under jit.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.autograd import apply

__all__ = ["stft", "istft", "frame", "overlap_add"]


def _frame_val(v, frame_length, hop_length, axis):
    # the literal axis value picks the layout (reference: axis=0 puts
    # frames leading even on 1-D input, axis=-1 puts them trailing)
    if axis == 0:
        seq = v.shape[0]
        n_frames = 1 + (seq - frame_length) // hop_length
        idx = (hop_length * jnp.arange(n_frames)[:, None]
               + jnp.arange(frame_length)[None, :])           # [nf, fl]
        return v[idx]                                         # [nf, fl, ...]
    if axis in (-1, v.ndim - 1):
        seq = v.shape[-1]
        n_frames = 1 + (seq - frame_length) // hop_length
        idx = (jnp.arange(frame_length)[:, None]
               + hop_length * jnp.arange(n_frames)[None, :])  # [fl, nf]
        return v[..., idx]                                    # [..., fl, nf]
    raise ValueError(f"frame: axis must be 0 or -1, got {axis}")


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice input into (overlapping) frames along `axis` (0 or -1)."""
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length must be positive")
    seq = x.shape[0] if axis == 0 else x.shape[-1]
    if frame_length > seq:
        raise ValueError(
            f"frame_length ({frame_length}) must not exceed the input size "
            f"along axis {axis} ({seq})")
    return apply(lambda v: _frame_val(v, frame_length, hop_length, axis), x)


def _overlap_add_val(v, hop_length, axis):
    if axis == 0:
        nf, fl = v.shape[0], v.shape[1]
        out_len = (nf - 1) * hop_length + fl
        pos = (hop_length * jnp.arange(nf)[:, None]
               + jnp.arange(fl)[None, :]).reshape(-1)
        vals = v.reshape((nf * fl,) + v.shape[2:])
        out = jnp.zeros((out_len,) + v.shape[2:], v.dtype)
        return out.at[pos].add(vals)
    if axis in (-1, v.ndim - 1):
        fl, nf = v.shape[-2], v.shape[-1]
        out_len = (nf - 1) * hop_length + fl
        pos = (jnp.arange(fl)[:, None]
               + hop_length * jnp.arange(nf)[None, :]).reshape(-1)
        vals = v.reshape(v.shape[:-2] + (fl * nf,))
        out = jnp.zeros(v.shape[:-2] + (out_len,), v.dtype)
        return out.at[..., pos].add(vals)
    raise ValueError(f"overlap_add: axis must be 0 or -1, got {axis}")


def overlap_add(x, hop_length, axis=-1, name=None):
    """Reconstruct a signal from framed slices by summing overlaps."""
    if hop_length <= 0:
        raise ValueError("hop_length must be positive")
    return apply(lambda v: _overlap_add_val(v, hop_length, axis), x)


def _prep_window(window, win_length, n_fft, dtype):
    if window is None:
        w = jnp.ones((win_length,), dtype)
    else:
        w = window._value if hasattr(window, "_value") else jnp.asarray(window)
        if w.shape != (win_length,):
            raise ValueError(
                f"window must have shape [{win_length}], got {list(w.shape)}")
    if win_length < n_fft:  # center-pad to n_fft
        lpad = (n_fft - win_length) // 2
        w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
    return w


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """STFT of a real/complex signal `[..., seq_len]` ->
    `[..., n_fft//2+1 | n_fft, num_frames]` complex."""
    hop_length = int(n_fft // 4) if hop_length is None else hop_length
    win_length = n_fft if win_length is None else win_length
    if not 0 < win_length <= n_fft:
        raise ValueError(f"win_length must be in (0, {n_fft}]")

    def _stft(v, w):
        is_cplx = jnp.issubdtype(v.dtype, jnp.complexfloating)
        if onesided and is_cplx:
            raise ValueError("onesided must be False for complex input")
        squeeze = v.ndim == 1
        if squeeze:
            v = v[None]
        if center:
            pad = n_fft // 2
            v = jnp.pad(v, [(0, 0)] * (v.ndim - 1) + [(pad, pad)],
                        mode=pad_mode)
        frames = _frame_val(v, n_fft, hop_length, -1)   # [..., n_fft, nf]
        frames = frames * w[:, None].astype(frames.dtype)
        if onesided and not is_cplx:
            spec = jnp.fft.rfft(frames, n=n_fft, axis=-2)
        else:
            spec = jnp.fft.fft(frames, n=n_fft, axis=-2)
        if normalized:
            spec = spec * (n_fft ** -0.5)
        return spec[0] if squeeze else spec

    return apply(_stft, x, _prep_window(window, win_length, n_fft,
                                        jnp.float32))


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Least-squares inverse of `stft`: `[..., freq, num_frames]` complex ->
    `[..., seq_len]`."""
    hop_length = int(n_fft // 4) if hop_length is None else hop_length
    win_length = n_fft if win_length is None else win_length
    if return_complex and onesided:
        raise ValueError("return_complex requires onesided=False")

    def _istft(v, w):
        squeeze = v.ndim == 2
        if squeeze:
            v = v[None]
        expected_freq = n_fft // 2 + 1 if onesided else n_fft
        if v.shape[-2] != expected_freq:
            raise ValueError(
                f"istft: input freq axis must be {expected_freq} "
                f"({'onesided' if onesided else 'twosided'}, n_fft={n_fft}), "
                f"got {v.shape[-2]}")
        n_frames = v.shape[-1]
        if normalized:
            v = v * (n_fft ** 0.5)
        if onesided:
            frames = jnp.fft.irfft(v, n=n_fft, axis=-2)
        elif return_complex:
            frames = jnp.fft.ifft(v, n=n_fft, axis=-2)
        else:
            frames = jnp.fft.ifft(v, n=n_fft, axis=-2).real
        frames = frames * w[:, None].astype(frames.dtype)
        y = _overlap_add_val(frames, hop_length, -1)
        env = _overlap_add_val(
            jnp.broadcast_to((w * w)[:, None], (n_fft, n_frames)),
            hop_length, -1)
        y = y / jnp.where(jnp.abs(env) > 1e-11, env, 1.0).astype(y.dtype)
        expected = (n_frames - 1) * hop_length + n_fft
        start = n_fft // 2 if center else 0
        out_len = (length if length is not None
                   else expected - 2 * start)
        y = y[..., start:start + out_len]
        if y.shape[-1] < out_len:
            y = jnp.pad(y, [(0, 0)] * (y.ndim - 1)
                        + [(0, out_len - y.shape[-1])])
        return y[0] if squeeze else y

    return apply(_istft, x, _prep_window(window, win_length, n_fft,
                                         jnp.float32))
