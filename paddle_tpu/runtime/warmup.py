"""Warm-start subsystem: persistent compile cache + shape-manifest AOT
precompile.

Every fresh process pays full XLA compilation for every (op, aval)
signature the jit-cached eager dispatcher (core/dispatch.py) and the
fused hapi/optimizer steps serve — time-to-first-step is pure retrace
cost, exactly the eager/compiler tension LazyTensor describes and the
reuse-compiled-artifacts discipline TVM builds its pipeline around.
This module makes repeated runs (CI, bench rounds, resumed training
after a rollback/restart) start hot:

* **Persistent compile cache** — `configure_compile_cache()` wires
  jax's on-disk executable cache (`jax_compilation_cache_dir`) into the
  framework. Opt-in via ``PADDLE_TPU_COMPILE_CACHE_DIR`` (auto-applied
  at import when set) with safe defaults: a min-compile-time threshold
  (``PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_S``, default 0 — the
  dispatch warm-count gate already keeps one-shot shapes out), a
  bounded directory with LRU eviction of cache files
  (``PADDLE_TPU_COMPILE_CACHE_MAX_BYTES``, default 2 GiB, enforced by
  jax's atime-based LRUCache), and corrupt-entry tolerance: a torn or
  bit-rotted cache file degrades to a fresh compile, observable as a
  ``compile_cache_errors`` fault event (PR-3 registry), never a crash.

* **Shape manifest** — dispatch records every compiled (op, treedef,
  statics, avals) signature here; the fused hapi/optimizer steps record
  their whole-program signatures via `record_program`. `save_manifest`
  serializes them to a versioned JSON file (automatically at process
  exit when ``PADDLE_TPU_SHAPE_MANIFEST`` names a path), and
  `precompile(manifest)` AOT-lowers/compiles those signatures at
  startup: per-op entries are rebuilt (module+code-object resolution,
  thawed closure cells/statics) and installed directly into the
  dispatch FORWARD cache as AOT executables; whole-step entries park in
  a pending table that registered warmup hooks (`prewarm_program`,
  called by `Model.warm_start` / `Optimizer.warm_start`) drain with
  `jit_fn.lower(avals).compile()`. With the disk cache enabled each of
  those compiles is a disk load, so a warm process performs **zero
  fresh XLA compiles** for recorded signatures.

* **Compile-time observability** — jax monitoring listeners count
  disk-cache hits vs fresh backend compiles and cumulative compile
  seconds; dispatch adds per-op compile seconds; `note_first_step`
  latches time-to-first-step per engine. All of it surfaces in
  `dispatch_stats()["compile"]` and `profiler.summary`.

A stale manifest (different jax / paddle_tpu / manifest version, or a
signature whose op no longer resolves) degrades to a cold start with a
``stale_manifests`` fault event — never an error. Cache-dir contention
from concurrent processes (bench child respawns) is safe by
construction: jax's cache writes are atomic renames and the key is
content-addressed, so the worst case is a duplicated compile.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
import types
import warnings

import numpy as np

from . import telemetry as _telemetry
from .resilience import atomic_write_json, record_fault

__all__ = [
    "configure_compile_cache", "compile_cache_config", "compile_metrics",
    "reset_compile_metrics", "note_first_step", "on_first_step_reset",
    "time_to_first_step",
    "reset_first_step", "note_op_compile", "record_op", "record_program",
    "record_trace",
    "manifest", "manifest_record_count", "save_manifest", "load_manifest",
    "rendezvous_manifest",
    "precompile", "prewarm_program", "pending_programs",
    "reset_manifest_records",
]

MANIFEST_VERSION = 1

_T0 = [time.monotonic()]
_lock = threading.Lock()

# global compile counters, fed by the jax monitoring listeners below.
# NOTE jax's backend_compile_duration event wraps compile_or_get_cached,
# so it fires on disk-cache HITS too — "fresh" compiles are derived as
# compile_calls - disk_cache_hits in compile_metrics().
_metrics = {
    "disk_cache_hits": 0,       # executables loaded from the on-disk cache
    "compile_calls": 0,         # executable requests (fresh OR disk load)
    "cache_requests": 0,        # compiles that consulted the disk cache
    "backend_compile_s": 0.0,   # cumulative seconds inside those requests
    "compile_time_saved_s": 0.0,  # jax's estimate of seconds disk hits saved
    "precompiled_ops": 0,       # manifest op entries installed into FORWARD
    "precompiled_programs": 0,  # whole-step signatures AOT-compiled
    "precompiled_traces": 0,    # fused-trace entries installed (fusion)
    "manifest_unreplayable": 0,  # replayable:false entries skipped by
    #                              precompile (unencodable statics /
    #                              unresolvable impls — coverage gaps a
    #                              warm start cannot absorb)
}
_first_step = {}  # engine kind -> seconds from _T0 to first compiled step

_cache_config = None  # effective config dict once configure() ran


# ---------------------------------------------------------------------------
# jax monitoring bridge (cheap counters; installed once at import)

def _on_event(event, **kw):
    if event == "/jax/compilation_cache/cache_hits":
        with _lock:
            _metrics["disk_cache_hits"] += 1
        _telemetry.emit("compile_cache_hit")
    elif event == "/jax/compilation_cache/compile_requests_use_cache":
        with _lock:
            _metrics["cache_requests"] += 1


def _on_duration(event, duration, **kw):
    if event == "/jax/core/compile/backend_compile_duration":
        with _lock:
            _metrics["compile_calls"] += 1
            _metrics["backend_compile_s"] += duration
        # one structured event per executable request (fresh compile OR
        # disk load — compiles are seconds-rare, so per-event cost is
        # noise): the time axis the aggregate counters lack
        _telemetry.emit("compile", seconds=round(duration, 6))
    elif event == "/jax/compilation_cache/compile_time_saved_sec":
        with _lock:
            _metrics["compile_time_saved_s"] += max(0.0, duration)


def _install_monitoring():
    """Runs at import (dispatch imports this module): a jax that moved
    its private monitoring API must degrade to zeroed compile counters,
    never an unimportable package."""
    try:
        from jax._src import monitoring as _mon

        if _on_event not in _mon.get_event_listeners():
            _mon.register_event_listener(_on_event)
        if _on_duration not in _mon.get_event_duration_listeners():
            _mon.register_event_duration_secs_listener(_on_duration)
    except Exception:  # pragma: no cover — jax internals moved
        pass


_install_monitoring()


def compile_metrics():
    """Snapshot of the global compile counters (+ cache dir, first-step).
    ``fresh_compiles`` is the number of executable requests the disk
    cache did NOT absorb — the quantity warm-start drives to zero."""
    with _lock:
        out = dict(_metrics)
        out["time_to_first_step_s"] = dict(_first_step)
    out["fresh_compiles"] = max(
        0, out["compile_calls"] - out["disk_cache_hits"])
    out["cache_dir"] = (_cache_config or {}).get("cache_dir")
    return out


def reset_compile_metrics():
    with _lock:
        for k in _metrics:
            _metrics[k] = 0.0 if isinstance(_metrics[k], float) else 0


# ---------------------------------------------------------------------------
# time-to-first-step latch

def note_first_step(kind):
    """Latch time-to-first-step for one engine ('eager_op', 'hapi_step',
    'fused_step'); later calls with the same kind are no-ops (one dict
    membership test — safe on the dispatch hot path)."""
    if kind in _first_step:
        return
    with _lock:
        _first_step.setdefault(kind, time.monotonic() - _T0[0])


def time_to_first_step():
    with _lock:
        return dict(_first_step)


_first_step_reset_hooks = []


def on_first_step_reset(cb):
    """Register a callback run by reset_first_step — engines keeping a
    local first-execution flag (dispatch's hot path) re-arm through
    this."""
    _first_step_reset_hooks.append(cb)


def reset_first_step():
    """Re-arm the latch with a fresh epoch (bench measures per config)."""
    with _lock:
        _first_step.clear()
        _T0[0] = time.monotonic()
    for cb in _first_step_reset_hooks:
        try:
            cb()
        except Exception:  # noqa: BLE001 — a bad hook must not break reset
            pass


def note_op_compile(name, seconds):
    """Cumulative compile seconds for a named whole-step program (the
    per-eager-op analogue lives in dispatch's _op_stats)."""
    with _lock:
        _program_compile_s[name] = _program_compile_s.get(name, 0.0) + seconds


_program_compile_s = {}


def program_compile_seconds():
    with _lock:
        return dict(_program_compile_s)


# ---------------------------------------------------------------------------
# persistent compile cache wiring

def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


def _patch_cache_error_observability():
    """Record a ``compile_cache_errors`` fault event whenever jax's
    persistent cache fails to read/write an entry (corrupt file, torn
    write, permission). jax already degrades to a fresh compile when
    ``jax_raise_persistent_cache_errors`` is False — this wrapper only
    makes the degradation observable; it re-raises so jax's own
    handling is unchanged. Patching failure degrades to no
    observability, never an import error."""
    try:
        from jax._src import compilation_cache as _cc

        if getattr(_cc, "_paddle_tpu_fault_wrapped", False):
            return
        _orig_get = _cc.get_executable_and_time
        _orig_put = _cc.put_executable_and_time

        def _get(*a, **kw):
            try:
                return _orig_get(*a, **kw)
            except Exception as e:
                record_fault("compile_cache_errors",
                             f"read: {type(e).__name__}: {e}"[:200])
                raise

        def _put(*a, **kw):
            try:
                return _orig_put(*a, **kw)
            except Exception as e:
                record_fault("compile_cache_errors",
                             f"write: {type(e).__name__}: {e}"[:200])
                raise

        _cc.get_executable_and_time = _get
        _cc.put_executable_and_time = _put
        _cc._paddle_tpu_fault_wrapped = True
    except Exception:  # pragma: no cover — jax internals moved
        pass


def configure_compile_cache(cache_dir=None, min_compile_secs=None,
                            max_bytes=None):
    """Wire jax's persistent compilation cache. Returns the effective
    config dict, or None when no directory is configured (arg or
    ``PADDLE_TPU_COMPILE_CACHE_DIR``). Safe to call repeatedly."""
    global _cache_config
    cache_dir = cache_dir or os.environ.get("PADDLE_TPU_COMPILE_CACHE_DIR")
    if not cache_dir:
        return None
    import jax

    cache_dir = os.path.abspath(cache_dir)
    os.makedirs(cache_dir, exist_ok=True)
    if min_compile_secs is None:
        # 0 by default: the per-op programs the eager dispatcher serves
        # compile in tens of ms each but number in the hundreds — they
        # are exactly what warm-start exists for. The dispatch
        # warm-count gate already keeps one-shot shapes from compiling
        # at all, and the LRU size bound caps total disk use.
        min_compile_secs = _env_float(
            "PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_S", 0.0)
    if max_bytes is None:
        max_bytes = int(_env_float("PADDLE_TPU_COMPILE_CACHE_MAX_BYTES",
                                   2 * 1024 ** 3))
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_secs))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # bounded dir: jax's LRUCache evicts least-recently-used entry files
    # (atime sidecars) once the dir exceeds max_size
    jax.config.update("jax_compilation_cache_max_size", int(max_bytes))
    # a corrupt entry must degrade to a fresh compile, not an error
    jax.config.update("jax_raise_persistent_cache_errors", False)
    _patch_cache_error_observability()
    try:
        # jax initializes its cache handle at most once per process; a
        # dir configured AFTER the first compile would otherwise be
        # silently ignored until restart
        from jax._src import compilation_cache as _cc

        live = getattr(_cc, "_cache", None)
        live_dir = getattr(live, "_path", None)
        if live is None or live_dir is None or str(live_dir) != cache_dir:
            _cc.reset_cache()
    except Exception:  # pragma: no cover — jax internals moved
        pass
    _cache_config = {
        "cache_dir": cache_dir,
        "min_compile_secs": float(min_compile_secs),
        "max_bytes": int(max_bytes),
    }
    return dict(_cache_config)


def compile_cache_config():
    return dict(_cache_config) if _cache_config else None


# ---------------------------------------------------------------------------
# serialization of signatures
#
# A manifest entry must survive JSON and reconstruct, in a fresh
# process, the exact cache key dispatch would build for the same call:
# the op's code object (resolved from its defining module), thawed
# closure cells / defaults / static args, the (args, kwargs) treedef,
# and array avals. Anything that cannot round-trip marks the entry
# non-replayable — it is still recorded (observability) but skipped by
# precompile.

_MARKER = "\x00leaf"


def _encode_key(k):
    """Dict keys: str or int/bool only (what framework pytrees use)."""
    if isinstance(k, str):
        return k
    if isinstance(k, bool) or not isinstance(k, int):
        raise TypeError(f"unencodable dict key {type(k).__name__}")
    return {"i": k}


def _decode_key(e):
    return e if isinstance(e, str) else e["i"]


def _encode_static(v):
    """JSON encoding for a static (non-array) value, preserving the type
    distinctions freeze_static keys on. Raises TypeError when `v` has no
    faithful encoding."""
    # EXACT types throughout: freeze_static type-tags numerics, so an
    # np.float64 or IntEnum static decoded as plain float/int would
    # rebuild a key that can never match (and numpy reprs don't even
    # parse) — refuse (-> non-replayable) instead
    if v is None or type(v) is bool or type(v) is str:
        return v
    if type(v) is int:
        return {"i": v}  # JSON round-trips int exactly
    if type(v) is float:
        return {"f": repr(v)}  # repr round-trips inf/-0.0; nan via float()
    # EXACT types only: a namedtuple or OrderedDict flattens to a
    # different treedef than the plain tuple/dict it would decode to —
    # coercing would mark the entry replayable under a key that can
    # never match real dispatch traffic
    if type(v) is tuple:
        return {"t": [_encode_static(x) for x in v]}
    if type(v) is list:
        return {"l": [_encode_static(x) for x in v]}
    if type(v) is dict:
        # keys as encoded pairs: JSON objects only take str keys, but
        # framework trees use int keys too (optimizer state slots)
        return {"d": [[_encode_key(k), _encode_static(x)]
                      for k, x in v.items()]}
    if isinstance(v, slice):
        return {"sl": [_encode_static(v.start), _encode_static(v.stop),
                       _encode_static(v.step)]}
    if isinstance(v, np.dtype):
        return {"npdt": v.name}
    from ..core import dtype as _pdt

    if isinstance(v, _pdt.dtype):
        return {"pdt": v.name}
    raise TypeError(f"unencodable static {type(v).__name__}")


def _decode_static(e):
    if e is None or isinstance(e, (bool, str)):
        return e
    tag, payload = next(iter(e.items()))
    if tag == "i":
        return payload
    if tag == "f":
        return float(payload)
    if tag == "t":
        return tuple(_decode_static(x) for x in payload)
    if tag == "l":
        return [_decode_static(x) for x in payload]
    if tag == "d":
        return {_decode_key(k): _decode_static(x) for k, x in payload}
    if tag == "sl":
        return slice(*[_decode_static(x) for x in payload])
    if tag == "npdt":
        return np.dtype(payload)
    if tag == "pdt":
        from ..core import dtype as _pdt

        return getattr(_pdt, payload)
    raise TypeError(f"unknown static tag {tag}")


def _encode_treedef(treedef, n_leaves):
    """Encode a treedef as a JSON skeleton whose leaves are markers.
    Only tuple/list/dict/None interior nodes are supported — anything
    else (a custom pytree node) raises TypeError."""
    import jax

    skel = jax.tree_util.tree_unflatten(treedef, [_MARKER] * n_leaves)

    def enc(node):
        if isinstance(node, str) and node == _MARKER:
            return _MARKER
        if node is None:
            return {"none": 0}
        # EXACT types: namedtuple/OrderedDict/defaultdict pytree nodes
        # flatten differently from the plain containers they would
        # decode to — refuse (-> non-replayable) rather than record a
        # key that can never hit
        if type(node) is tuple:
            return {"t": [enc(x) for x in node]}
        if type(node) is list:
            return {"l": [enc(x) for x in node]}
        if type(node) is dict:
            return {"d": [[_encode_key(k), enc(v)]
                          for k, v in node.items()]}
        raise TypeError(f"unsupported pytree node {type(node).__name__}")

    return enc(skel)


class _Leaf:
    """Placeholder leaf for treedef reconstruction (treated as a pytree
    leaf by flatten because it is an unregistered object)."""


def _decode_treedef(enc):
    """Rebuild the treedef (and leaf count) from a skeleton encoding."""
    import jax

    def dec(node):
        if isinstance(node, str) and node == _MARKER:
            return _Leaf()
        tag, payload = next(iter(node.items()))
        if tag == "none":
            return None
        if tag == "t":
            return tuple(dec(x) for x in payload)
        if tag == "l":
            return [dec(x) for x in payload]
        if tag == "d":
            return {_decode_key(k): dec(v) for k, v in payload}
        raise TypeError(f"unknown treedef tag {tag}")

    skel = dec(enc)
    leaves, treedef = jax.tree_util.tree_flatten(skel)
    return treedef, len(leaves)


def _encode_aval(shape, dtype, weak):
    return {"a": [list(int(d) for d in shape), str(np.dtype(dtype).name),
                  bool(weak)]}


def _decode_aval(e):
    import jax

    shape, dtype, weak = e["a"]
    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype),
                                weak_type=bool(weak))


def _code_ref(code):
    """Locatable reference for a code object: defining module (import
    name), co_name, co_firstlineno. The path suffix is recorded for
    diagnostics only — resolution goes through the import system."""
    path = code.co_filename.replace(os.sep, "/")
    i = path.rfind("paddle_tpu/")
    return {"path": path[i:] if i >= 0 else os.path.basename(path),
            "name": code.co_name, "line": code.co_firstlineno}


def _index_module_codes(mod):
    """(co_name, co_firstlineno) -> code object, over every function
    defined at module top level, in classes, and nested inside them
    (walking co_consts reaches lambdas and `def _f` helpers)."""
    seen = {}
    stack = []
    for v in vars(mod).values():
        if isinstance(v, types.FunctionType) and v.__module__ == mod.__name__:
            stack.append(v.__code__)
        elif isinstance(v, type) and getattr(v, "__module__", None) == \
                mod.__name__:
            for m in vars(v).values():
                f = getattr(m, "__func__", m)
                if isinstance(f, types.FunctionType):
                    stack.append(f.__code__)
    while stack:
        code = stack.pop()
        k = (code.co_name, code.co_firstlineno)
        if k in seen:
            continue
        seen[k] = code
        for c in code.co_consts:
            if isinstance(c, types.CodeType):
                stack.append(c)
    return seen


_code_index_cache = {}


def _resolve_code(module_name, ref):
    import importlib

    idx = _code_index_cache.get(module_name)
    if idx is None:
        mod = importlib.import_module(module_name)
        idx = _index_module_codes(mod)
        _code_index_cache[module_name] = idx
    return idx.get((ref["name"], ref["line"]))


def _rebuild_fn(entry):
    """Reconstruct the op callable for a manifest entry: resolve the
    code object from its defining module, thaw closure cells and
    defaults. Returns None when anything fails to resolve (source
    drift) — the caller counts it stale."""
    import importlib

    impl = entry["impl"]
    mod_name = impl["module"]
    mod = importlib.import_module(mod_name)
    if impl.get("attr"):
        # module-level singleton (jnp ufunc, custom_jvp wrapper): the
        # live attribute IS the callable
        fn = mod
        for part in impl["attr"].split("."):
            fn = getattr(fn, part)
        return fn
    code = _resolve_code(mod_name, impl["code"])
    if code is None:
        return None
    cells = None
    if impl.get("cells") is not None:
        vals = [_decode_static(c) for c in impl["cells"]]
        if len(vals) != len(code.co_freevars):
            return None
        cells = tuple(types.CellType(v) for v in vals)
    dflt = None
    if impl.get("defaults") is not None:
        dflt = tuple(_decode_static(d) for d in impl["defaults"])
    fn = types.FunctionType(code, vars(mod), code.co_name, dflt, cells)
    if impl.get("kwdefaults") is not None:
        fn.__kwdefaults__ = {k: _decode_static(v)
                             for k, v in impl["kwdefaults"].items()}
    return fn


def _encode_impl(fn):
    """Replayable reference to the op callable, or None. Plain functions
    encode (module, code ref, cells, defaults); known stateless
    singletons (jnp ufuncs, pre-jitted jnp ops, custom_jvp wrappers)
    encode the module attribute path that resolves to the same object."""
    if isinstance(fn, types.FunctionType):
        mod = fn.__globals__.get("__name__")
        if not mod:
            return None
        impl = {"module": mod, "code": _code_ref(fn.__code__)}
        if fn.__closure__:
            impl["cells"] = [_encode_static(c.cell_contents)
                             for c in fn.__closure__]
        if fn.__defaults__:
            impl["defaults"] = [_encode_static(d) for d in fn.__defaults__]
        if fn.__kwdefaults__:
            impl["kwdefaults"] = {k: _encode_static(v)
                                  for k, v in fn.__kwdefaults__.items()}
        return impl
    # non-function callables: resolvable only as a module attribute
    mod = getattr(fn, "__module__", None)
    name = getattr(fn, "__qualname__", None) or getattr(fn, "__name__", None)
    if not mod or not name or "<" in name:
        return None
    import importlib

    try:
        obj = importlib.import_module(mod)
        for part in name.split("."):
            obj = getattr(obj, part)
    except Exception:
        return None
    if obj is not fn:
        return None
    return {"module": mod, "attr": name}


# ---------------------------------------------------------------------------
# the recorder

_records = {}          # fingerprint -> op entry dict
_program_records = {}  # fingerprint -> program entry dict
_RECORD_CAP = 4096


def record_op(fn, name, treedef, vals, arr_pos, avals):
    """Called by dispatch after the first successful execution of a
    freshly compiled per-op program. Never raises."""
    if len(_records) >= _RECORD_CAP:
        return
    try:
        entry = {"kind": "op", "name": name, "impl": None, "tree": None,
                 "leaves": None, "replayable": False}
        try:
            impl = _encode_impl(fn)
            arr = dict(zip(arr_pos, avals))
            merged = []
            for i, v in enumerate(vals):
                if i in arr:
                    shape, dtype, weak = arr[i]
                    merged.append(_encode_aval(shape, dtype, weak))
                else:
                    merged.append({"s": _encode_static(v)})
            entry.update(impl=impl, leaves=merged,
                         tree=_encode_treedef(treedef, len(vals)),
                         replayable=impl is not None)
        except TypeError:
            pass  # recorded for observability, skipped by precompile
        fp = json.dumps(entry, sort_keys=True, default=str)
        with _lock:
            _records.setdefault(fp, entry)
    except Exception:  # noqa: BLE001 — recording must never break dispatch
        pass


def record_trace(entry):
    """Record one fused-trace entry (built by core/fusion.py at a fresh
    fused build: per-node op encodings + dataflow wiring + external
    avals + live-output mask). Stored alongside per-op entries so
    `save_manifest` persists it and `precompile` replays it through
    `fusion.precompile_trace`. Never raises."""
    try:
        if len(_records) >= _RECORD_CAP:
            return
        fp = json.dumps(entry, sort_keys=True, default=str)
        with _lock:
            _records.setdefault(fp, entry)
    except Exception:  # noqa: BLE001 — recording must never break a flush
        pass


def record_program(name, args):
    """Record a whole-step jit program's input signature (pytree of
    arrays/statics) under `name` ('hapi.train_step',
    'optimizer.fused_step.SGD', ...). Called by the owner right before
    its first compiled call. Never raises."""
    try:
        import jax

        if len(_program_records) >= _RECORD_CAP:
            return
        leaves, treedef = jax.tree_util.tree_flatten(args)
        enc = []
        for v in leaves:
            if isinstance(v, (jax.Array, np.ndarray)):
                enc.append(_encode_aval(v.shape, v.dtype,
                                        bool(getattr(v, "weak_type", False))))
            else:
                enc.append({"s": _encode_static(v)})
        entry = {"kind": "program", "name": name, "leaves": enc,
                 "tree": _encode_treedef(treedef, len(leaves)),
                 "replayable": True}
        fp = json.dumps(entry, sort_keys=True, default=str)
        with _lock:
            _program_records.setdefault(fp, entry)
    except Exception:  # noqa: BLE001
        pass


def _versions():
    import jax

    try:
        from .. import version as _v

        pt = _v.full_version
    except Exception:  # pragma: no cover
        pt = "unknown"
    return {"jax": jax.__version__, "paddle_tpu": pt}


def manifest_record_count():
    """Number of signatures recorded so far (ops + programs)."""
    with _lock:
        return len(_records) + len(_program_records)


def manifest():
    """The current recorded signatures as a versioned manifest dict."""
    with _lock:
        entries = list(_records.values()) + list(_program_records.values())
    return {"version": MANIFEST_VERSION, **_versions(),
            "created": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "entries": entries}


def save_manifest(path=None):
    """Write the manifest atomically. Default path:
    ``PADDLE_TPU_SHAPE_MANIFEST``. Returns the path, or None when there
    is nowhere to write."""
    path = path or os.environ.get("PADDLE_TPU_SHAPE_MANIFEST")
    if not path:
        return None
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    atomic_write_json(path, manifest())
    return path


def _validate_manifest_doc(doc, origin):
    """None when `doc` matches this process's versions, else degrades
    to a ``stale_manifests`` fault event and returns the reason."""
    vers = _versions()
    if doc.get("version") != MANIFEST_VERSION:
        reason = (f"manifest version {doc.get('version')} != "
                  f"{MANIFEST_VERSION}")
    else:
        reason = None
        for k in ("jax", "paddle_tpu"):
            if doc.get(k) != vers[k]:
                reason = f"{k} {doc.get(k)} != {vers[k]}"
                break
    if reason is not None:
        record_fault("stale_manifests", f"{origin}: {reason}")
    return reason


def load_manifest(path):
    """Load + validate a manifest. A missing/corrupt/version-mismatched
    file degrades to None (cold start) with a ``stale_manifests`` fault
    event — a warm-start helper must never turn into a startup
    crash."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        record_fault("stale_manifests",
                     f"{path}: unreadable ({type(e).__name__})")
        return None
    if _validate_manifest_doc(doc, path) is not None:
        return None
    return doc


def rendezvous_manifest(cluster, path=None, timeout=60.0, min_wall=None):
    """Multihost warm start without the manifest race: host 0 saves the
    shape manifest (when `path` or ``PADDLE_TPU_SHAPE_MANIFEST`` names
    one) and publishes the full document through the coordination
    store's rendezvous; every peer waits-and-reads instead of N ranks
    racing one file (the PR-4 follow-up). Returns the manifest doc to
    feed `precompile`, or None when the rendezvous timed out or the
    published doc fails version validation — both degrade to a cold
    start (`rendezvous_timeouts` / `stale_manifests` fault events),
    never an exception at startup.

    A store dir REUSED across runs still holds the previous
    incarnation's publication; by default a follower accepts it (same
    versions — at worst some precompiles are stale, never wrong).
    Jobs whose shape set changes between runs should pass `min_wall`
    (this run's launch wall time) so followers wait for the new
    leader's document instead."""
    from ..distributed.coordination import rendezvous

    if cluster.is_leader:
        doc = manifest()
        try:
            save_manifest(path)
        except OSError as e:
            record_fault("stale_manifests",
                         f"manifest save before rendezvous: {e}")
        try:
            rendezvous(cluster.store, "shape_manifest", doc,
                       timeout=timeout, leader=True)
        except Exception as e:  # noqa: BLE001 — split/unwritable store:
            # the leader still warm-starts from its own doc; peers will
            # time out and cold-start with their own fault events
            record_fault("stale_manifests",
                         f"manifest rendezvous publish: "
                         f"{type(e).__name__}: {e}")
        return doc
    doc = rendezvous(cluster.store, "shape_manifest", timeout=timeout,
                     min_wall=min_wall)
    if doc is None:
        return None  # rendezvous_timeouts already recorded: cold start
    if _validate_manifest_doc(doc, "shape_manifest rendezvous") is not None:
        return None
    return doc


# ---------------------------------------------------------------------------
# precompile

_pending_programs = {}  # name -> [(fingerprint, args-template tree)]
_pending_fps = set()    # fingerprints currently parked (dedup across
#                         repeated precompile() calls; released on drain)


def pending_programs():
    return {k: len(v) for k, v in _pending_programs.items()}


def reset_manifest_records():
    """Drop all recorded signatures and pending program entries (test
    isolation; production processes accumulate for the exit-time
    save)."""
    with _lock:
        _records.clear()
        _program_records.clear()
        _program_compile_s.clear()
    _pending_programs.clear()
    _pending_fps.clear()


def _decode_leaves(entry):
    """leaves template: ShapeDtypeStruct at array slots, thawed statics
    elsewhere; plus the treedef."""
    treedef, n = _decode_treedef(entry["tree"])
    if n != len(entry["leaves"]):
        raise TypeError("leaf count mismatch")
    leaves = []
    for e in entry["leaves"]:
        if "a" in e:
            leaves.append(_decode_aval(e))
        else:
            leaves.append(_decode_static(e["s"]))
    return treedef, leaves


def _remember(entry):
    """Re-register a successfully replayed manifest entry into this
    process's recorder. Without this, a warm process's exit-time save
    would contain only its FRESH compiles (precompiled signatures never
    rebuild, so record_op never fires for them) and the manifest would
    decay toward empty across warm generations."""
    fp = json.dumps(entry, sort_keys=True, default=str)
    bucket = _program_records if entry.get("kind") == "program" else _records
    with _lock:
        bucket.setdefault(fp, entry)


def precompile(manifest_doc):
    """AOT-compile the signatures in `manifest_doc` (a dict from
    `manifest()`/`load_manifest`, or a path). Per-op entries are rebuilt
    and installed into the dispatch FORWARD cache as AOT executables;
    program entries are parked for `prewarm_program`. Every entry that
    replays is also carried forward into this process's own recorder,
    so a chain of warm restarts keeps a stable manifest. Returns a
    stats dict; with the persistent compile cache enabled every compile
    here is a disk load."""
    if isinstance(manifest_doc, str):
        manifest_doc = load_manifest(manifest_doc)
    stats = {"ops_precompiled": 0, "ops_skipped": 0, "programs_pending": 0,
             "traces_precompiled": 0, "stale": manifest_doc is None}
    if manifest_doc is None:
        return stats
    from ..core import dispatch as _dispatch

    unreplayable = []
    for entry in manifest_doc.get("entries", ()):
        if not entry.get("replayable"):
            stats["ops_skipped"] += 1
            unreplayable.append(str(entry.get("name") or "<unnamed>"))
            continue
        if entry.get("kind") == "trace":
            # fused eager trace (core/fusion.py): fully AOT-replayable
            # without any live model — rebuild the node chain, compile
            # the fused program (a disk load with the persistent
            # cache), install it under the reconstructed fingerprint
            try:
                from ..core import fusion as _fusion

                if _fusion.precompile_trace(entry):
                    stats["traces_precompiled"] += 1
                    _remember(entry)
                    with _lock:
                        _metrics["precompiled_traces"] += 1
                else:
                    stats["ops_skipped"] += 1
            except Exception:  # noqa: BLE001 — drift must not abort
                record_fault("stale_manifests",
                             f"trace entry {entry.get('name')}: "
                             "replay failed")
                stats["ops_skipped"] += 1
            continue
        if entry.get("kind") == "program":
            try:
                fp = json.dumps(entry, sort_keys=True, default=str)
                if fp in _pending_fps:
                    continue
                treedef, leaves = _decode_leaves(entry)
                import jax

                args = jax.tree_util.tree_unflatten(treedef, leaves)
                _pending_fps.add(fp)
                # NOT _remember()ed here: a program signature proves
                # itself live only when prewarm_program lowers it — a
                # stale one must age out of the manifest, not persist
                # through every future exit save
                _pending_programs.setdefault(entry["name"], []).append(
                    (fp, entry, args))
                stats["programs_pending"] += 1
            except Exception:  # noqa: BLE001 — one bad entry must not abort
                record_fault("stale_manifests",
                             f"program entry {entry.get('name')}")
                stats["ops_skipped"] += 1
            continue
        try:
            fn = _rebuild_fn(entry)
            if fn is None:
                record_fault("stale_manifests",
                             f"op entry {entry.get('name')}: unresolvable")
                stats["ops_skipped"] += 1
                continue
            treedef, leaves = _decode_leaves(entry)
            if _dispatch.precompile_op(fn, treedef, leaves,
                                       name=entry.get("name")):
                stats["ops_precompiled"] += 1
                _remember(entry)
                with _lock:
                    _metrics["precompiled_ops"] += 1
            else:
                stats["ops_skipped"] += 1
        except Exception:  # noqa: BLE001
            record_fault("stale_manifests",
                         f"op entry {entry.get('name')}: replay failed")
            stats["ops_skipped"] += 1
    if unreplayable:
        stats["ops_unreplayable"] = len(unreplayable)
        with _lock:
            _metrics["manifest_unreplayable"] += len(unreplayable)
        _warn_unreplayable(unreplayable)
    _telemetry.emit("precompile", **stats)
    return stats


_warned_unreplayable = False


def _warn_unreplayable(names):
    """Log ONCE per process which manifest entries a warm start cannot
    replay (``replayable: false`` — statics/impls with no faithful JSON
    encoding). Their compiles stay cold on every restart; the count is
    surfaced in ``dispatch_stats()["compile"]["manifest_unreplayable"]``
    so the coverage gap is visible without log archaeology."""
    global _warned_unreplayable
    if _warned_unreplayable:
        return
    _warned_unreplayable = True
    counts = {}
    for n in names:
        counts[n] = counts.get(n, 0) + 1
    shown = sorted(counts)[:8]
    more = "" if len(counts) <= 8 else f" (+{len(counts) - 8} more ops)"
    warnings.warn(
        "paddle_tpu warm start: skipped "
        f"{len(names)} non-replayable manifest entr"
        f"{'y' if len(names) == 1 else 'ies'} during precompile — these "
        "ops will compile fresh on every restart. Ops: "
        + ", ".join(f"{n} x{counts[n]}" for n in shown) + more,
        stacklevel=3)


def prewarm_program(name, jit_fn):
    """Warmup hook for whole-step programs: AOT-lower/compile every
    pending manifest signature recorded under `name` against `jit_fn`.
    Entries that no longer trace (model changed shape) degrade to a
    ``stale_manifests`` fault event. Returns the number compiled."""
    pending = _pending_programs.pop(name, None)
    if not pending:
        return 0
    n = 0
    for fp, entry, args in pending:
        _pending_fps.discard(fp)  # a later precompile() may re-park it
        try:
            t0 = time.perf_counter()
            jit_fn.lower(*args).compile()
            note_op_compile(name, time.perf_counter() - t0)
            n += 1
            _remember(entry)  # proven live: carry into this process's
            #                   manifest so warm chains stay stable
            with _lock:
                _metrics["precompiled_programs"] += 1
        except Exception as e:  # noqa: BLE001 — stale signature
            record_fault("stale_manifests",
                         f"{name}: {type(e).__name__}"[:120])
    _telemetry.emit("precompile", program=name, compiled=n)
    return n


# ---------------------------------------------------------------------------
# process wiring: env-driven auto-config + exit-time manifest save

if os.environ.get("PADDLE_TPU_COMPILE_CACHE_DIR"):
    try:
        configure_compile_cache()
    except Exception:  # pragma: no cover — never break import
        pass

if os.environ.get("PADDLE_TPU_SHAPE_MANIFEST"):
    def _exit_save():
        try:
            # a process that recorded nothing (utility script importing
            # the package under a job-wide env var) must not clobber a
            # previously recorded manifest with an empty one — warm
            # processes re-register what they precompiled, so a real
            # workload always has records here
            if manifest_record_count() > 0:
                save_manifest()
        except Exception:  # noqa: BLE001 — exit path
            pass

    atexit.register(_exit_save)
