"""Memory stats (reference: fluid/memory allocator stats; paddle.device.cuda
memory API). The XLA arena owns HBM; these report what it exposes."""
from __future__ import annotations

import jax

__all__ = ["memory_allocated", "max_memory_allocated", "memory_reserved",
           "max_memory_reserved", "memory_stats"]


def _stats(device=None):
    try:
        d = jax.devices()[0] if device is None else device
        return d.memory_stats() or {}
    except Exception:  # noqa: BLE001 - CPU backend has no stats
        return {}


def memory_allocated(device=None):
    return int(_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None):
    return int(_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None):
    s = _stats(device)
    return int(s.get("bytes_reserved", s.get("bytes_limit", 0)))


def max_memory_reserved(device=None):
    return int(_stats(device).get("bytes_limit", 0))


def memory_stats(device=None):
    return dict(_stats(device))
