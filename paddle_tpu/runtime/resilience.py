"""Fault-tolerant training runtime: fault events, retry/backoff, fault
injection, and the bad-step guard.

A long TPU run dies from exactly the failures the happy path never
exercises: a transient I/O error during a checkpoint write, a `kill -9`
mid-async-save, a corrupted shard on restore, a step loop that hangs
before the first heartbeat, a NaN loss that poisons the parameters.
This module is the shared substrate the rest of the stack hardens
itself with:

* **Fault-event registry** — `record_fault(kind)` / `fault_events()`:
  cheap, thread-safe counters (save_retries, restore_fallbacks,
  rollbacks, stall_detections, eager_demotions, ...) plus a bounded log
  of recent events. Degradation must be *observable*: every recovery
  path in io/checkpoint.py, distributed/elastic.py and core/dispatch.py
  bumps a counter here, and `dispatch_stats()` / `profiler.summary`
  surface the snapshot.
* **`retry_with_backoff`** — bounded retry with exponential backoff and
  full jitter for transient I/O errors. Checkpoint save/restore wrap
  their orbax calls in it.
* **`FaultInjector` / `fault_point`** — deterministic fault injection.
  Library code calls `fault_point("site")` at instrumented sites; an
  active injector (context manager, or env `PADDLE_TPU_FAULT_INJECT`
  for child processes) decides to raise on the nth call, raise
  transiently then succeed, SIGKILL the process, delay, or corrupt a
  file. This is how the crash-consistency suite makes "kill mid
  async save" and "transient IOError then succeed" reproducible.
* **`BadStepGuard`** — non-finite loss/grad sentinel: on a bad step it
  rolls state back via the caller's `rollback_fn` and, after N
  *consecutive* rollbacks, escalates (callback or `EscalationError`).

Everything here is host-side control plane: stdlib + numpy only, no
jax import, so `core.dispatch` can depend on it without a cycle.  None
of these functions may ever run under a trace — the wall-clock and
randomness they use (backoff sleeps, jitter) is exactly what tracelint
TL004 forbids in op bodies, which is why the elastic watchdog helpers
that ARE reachable from instrumented modules carry `@non_jittable` +
reviewed waivers instead of silently relying on never being dispatched.
"""
from __future__ import annotations

import collections
import json
import os
import random
import signal
import threading
import time
import warnings

import numpy as np

from . import telemetry as _telemetry

__all__ = [
    "fault_events", "fault_log", "record_fault", "reset_fault_events",
    "retry_with_backoff", "FaultInjector", "fault_point", "InjectedFault",
    "BadStepGuard", "EscalationError", "IntegrityError", "corrupt_file",
    "all_finite",
]


# ---------------------------------------------------------------------------
# fault-event registry

# known counters, pre-zeroed so fault_events() always reports the full
# vocabulary (an absent key would read as "this path can't happen")
_EVENT_KINDS = (
    "save_retries",           # transient save I/O error, retried
    "save_failures",          # save gave up / async save surfaced an error
    "restore_retries",        # transient restore I/O error, retried
    "restore_fallbacks",      # a step failed verify/load; fell back to prior
    "rollbacks",              # BadStepGuard rolled state back
    "escalations",            # N consecutive rollbacks
    "stall_detections",       # watchdog fired (incl. missing 1st heartbeat)
    "watchdog_errors",        # watchdog loop survived its own exception
    "heartbeat_regressions",  # tick() called with a step older than recorded
    "eager_demotions",        # dispatch learned an op non-jittable at runtime
    "injected_faults",        # FaultInjector fired (test observability)
    "compile_cache_errors",   # persistent compile-cache entry failed to
    #                           read/write (corrupt file); degraded to a
    #                           fresh compile
    "fusion_demotions",       # an op raised under the fused trace and
    #                           was learned fusion-unsafe (flush-then-
    #                           eager from then on) — the fusion
    #                           engine's eager_demotions analogue
    "fusion_fallbacks",       # a fused program failed to compile/run
    #                           and the trace was replayed eagerly
    "stale_manifests",        # a warm-start shape manifest was rejected
    #                           (version mismatch, unresolvable op) or an
    #                           entry failed to replay; cold start instead
    "peer_stale",             # a cluster peer's heartbeat went stale
    #                           (single slow rank: degrade, don't abort)
    "peer_dead",              # a peer silent past the hard deadline was
    #                           declared down cluster-wide
    "rendezvous_timeouts",    # a rendezvous wait expired; caller degraded
    #                           (cold start / local fallback) instead of
    #                           hanging
    "push_failures",          # a pushgateway export failed; warned and
    #                           dropped, never raised into training
    "postmortem_failures",    # a diagnostics bundle dump failed (full
    #                           disk, serialization bug); the dying
    #                           process degraded to no evidence
    "statusz_errors",         # the /statusz server failed to bind or a
    #                           route handler raised; served degraded
    "data_worker_timeout",    # a DataLoader worker / prefetch producer
    #                           blew past timeout=; raised cleanly with
    #                           staged ring slots recycled
    "data_producer_died",     # a DevicePrefetcher's producer thread
    #                           died silently; the consumer degraded to
    #                           synchronous input instead of wedging fit
    "kv_preemptions",         # the serving scheduler evicted a running
    #                           sequence to free KV blocks (it re-queues
    #                           and recomputes; visible degradation)
    "paged_kernel_fallbacks",  # the ragged paged-attention kernel was
    #                           unavailable/failed and decode fell back
    #                           to the dense gather path
    "serve_sheds",            # admission control refused (or a queued
    #                           request out-waited max_queue_wait_s and
    #                           was dropped by) the serving engine —
    #                           the caller saw OverloadedError / an
    #                           `overloaded` outcome, never silence
    "journal_errors",         # a serving request-journal append or
    #                           compaction failed; the record was
    #                           dropped and serving continued (crash
    #                           recovery degrades, the engine does not)
    "access_log_errors",      # a serving access-log append/rotation
    #                           failed; the record was dropped (ring +
    #                           aggregates still updated) and serving
    #                           continued — same never-raise contract
    #                           as the journal
    "collective_divergence",  # two live ranks published collective-
    #                           schedule fingerprints that disagree at a
    #                           common sequence point — the SPMD
    #                           contract broke (ClusterMonitor, with
    #                           both ranks' schedule tails in the
    #                           detail; tools/distlint is the static
    #                           half of the same check)
)

_events_lock = threading.Lock()
_events = {k: 0 for k in _EVENT_KINDS}
_event_log = collections.deque(maxlen=256)


def record_fault(kind, detail=None):
    """Count one fault event; returns the new count for `kind`. Each
    fault also lands in the telemetry event stream (when configured) so
    a degradation can be correlated, post-hoc, with the training step
    that caused it — the counter alone has no time axis."""
    with _events_lock:
        n = _events.get(kind, 0) + 1
        _events[kind] = n
        _event_log.append((time.time(), kind, detail))
    _telemetry.emit("fault", fault=kind, detail=detail, count=n)
    return n


def fault_events():
    """Snapshot of all fault counters (always the full key vocabulary)."""
    with _events_lock:
        out = {k: 0 for k in _EVENT_KINDS}
        out.update(_events)
        return out


def fault_log(last=20):
    """Most recent (unix_time, kind, detail) events, oldest first."""
    with _events_lock:
        return list(_event_log)[-last:]


def reset_fault_events():
    with _events_lock:
        _events.clear()
        _events.update({k: 0 for k in _EVENT_KINDS})
        _event_log.clear()


# ---------------------------------------------------------------------------
# retry with exponential backoff + jitter

def retry_with_backoff(fn, *, attempts=4, base_delay=0.05, max_delay=2.0,
                       jitter=1.0, retry_on=(OSError,), counter=None,
                       describe="operation", on_retry=None):
    """Run `fn()`, retrying on `retry_on` with exponential backoff.

    Delay before attempt k (k>=1) is uniform(0, min(max_delay,
    base_delay * 2**(k-1)) * jitter_share) + deterministic share — i.e.
    "equal jitter": half the backoff is fixed, half randomized, so
    concurrent retriers decorrelate without ever retrying immediately.
    `counter` names the fault-event bumped per retry; the final failure
    re-raises the last exception (callers decide whether that degrades
    or propagates).
    """
    attempts = max(1, int(attempts))
    last = None
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop by design
            last = e
            if attempt == attempts - 1:
                raise
            if counter:
                record_fault(counter, f"{describe}: {type(e).__name__}: {e}")
            if on_retry is not None:
                on_retry(attempt + 1, e)
            cap = min(max_delay, base_delay * (2.0 ** attempt))
            half = cap / 2.0
            time.sleep(half + random.uniform(0.0, half) * jitter)
    raise last  # pragma: no cover — loop always returns or raises


# ---------------------------------------------------------------------------
# fault injection

class InjectedFault(IOError):
    """Raised by the injector at a fault point (an IOError so the
    production retry paths treat it exactly like a real transient)."""


class _FaultSpec:
    """One site's behavior.

    kind:
      raise      raise `exc` on the nth call (and every later call while
                 `count` calls remain; count=0 means every call)
      transient  raise `exc` for the first `count` calls, then succeed
      kill       SIGKILL the process on the nth call (kill -9 semantics:
                 no atexit, no finally — the crash-consistency hammer)
      delay      sleep `seconds` on every call from the nth on
      corrupt    corrupt the file/dir named by the fault point's `path`
                 payload (or `self.path`) on the nth call
    """

    def __init__(self, kind, nth=1, count=0, exc=InjectedFault,
                 seconds=0.05, path=None):
        self.kind = kind
        self.nth = max(1, int(nth))
        self.count = int(count)
        self.exc = exc
        self.seconds = float(seconds)
        self.path = path
        self.calls = 0
        self.fired = 0


class FaultInjector:
    """Deterministic fault injection, context-manager or env driven.

        with FaultInjector({"checkpoint.save": ("transient", 2)}):
            mngr.save(step, state)      # first 2 writes raise, 3rd lands

    Spec values are either a `_FaultSpec`, a dict of its kwargs, or a
    tuple `(kind, arg)` where arg is `count` for transient/raise,
    `seconds` for delay, and `nth` otherwise.

    Child processes (the `kill -9` crash tests) can't inherit a Python
    context manager, so the env var ``PADDLE_TPU_FAULT_INJECT`` carries
    the same specs: ``site=kind[:arg][;site=kind[:arg]...]`` — e.g.
    ``checkpoint.async_started=kill:1``.  The env injector is parsed
    lazily on the first fault_point() call.
    """

    _stack = []
    _stack_lock = threading.Lock()
    _env_injector = None

    def __init__(self, specs):
        self.specs = {site: self._coerce(spec)
                      for site, spec in (specs or {}).items()}

    @staticmethod
    def _coerce(spec):
        if isinstance(spec, _FaultSpec):
            return spec
        if isinstance(spec, dict):
            return _FaultSpec(**spec)
        kind, *rest = spec if isinstance(spec, (tuple, list)) else (spec,)
        arg = rest[0] if rest else None
        if kind == "transient":
            return _FaultSpec(kind, count=int(arg or 1))
        if kind == "raise":
            return _FaultSpec(kind, nth=1, count=int(arg or 0))
        if kind == "delay":
            return _FaultSpec(kind, seconds=float(arg or 0.05))
        if kind in ("kill", "corrupt"):
            return _FaultSpec(kind, nth=int(arg or 1))
        raise ValueError(f"unknown fault kind {kind!r}")

    # -- activation ---------------------------------------------------------
    def __enter__(self):
        with self._stack_lock:
            FaultInjector._stack.append(self)
        return self

    def __exit__(self, *exc):
        with self._stack_lock:
            FaultInjector._stack.remove(self)
        return False

    @classmethod
    def _active(cls):
        inj = list(cls._stack)
        env = cls._from_env()
        if env is not None:
            inj.append(env)
        return inj

    @classmethod
    def _from_env(cls):
        raw = os.environ.get("PADDLE_TPU_FAULT_INJECT", "")
        if not raw:
            cls._env_injector = None
            return None
        if cls._env_injector is not None and \
                cls._env_injector._env_raw == raw:
            return cls._env_injector
        specs = {}
        for part in raw.split(";"):
            part = part.strip()
            if not part or "=" not in part:
                continue
            site, _, rhs = part.partition("=")
            kind, *args = rhs.split(":")
            specs[site.strip()] = tuple([kind.strip()] + args)
        env = cls(specs)
        env._env_raw = raw
        cls._env_injector = env
        return env

    # -- firing -------------------------------------------------------------
    def fires(self, site):
        return site in self.specs

    def fire(self, site, info):
        spec = self.specs.get(site)
        if spec is None:
            return
        spec.calls += 1
        k = spec.kind
        if k == "transient":
            if spec.calls <= spec.count:
                spec.fired += 1
                record_fault("injected_faults", f"{site}:transient")
                raise spec.exc(f"injected transient fault at {site} "
                               f"(call {spec.calls}/{spec.count})")
            return
        if spec.calls < spec.nth:
            return
        if k == "raise":
            if spec.count and spec.calls >= spec.nth + spec.count:
                return
            spec.fired += 1
            record_fault("injected_faults", f"{site}:raise")
            raise spec.exc(f"injected fault at {site} (call {spec.calls})")
        if k == "kill":
            if spec.calls != spec.nth:
                return
            record_fault("injected_faults", f"{site}:kill")
            os.kill(os.getpid(), signal.SIGKILL)  # no return
        if k == "delay":
            spec.fired += 1
            record_fault("injected_faults", f"{site}:delay")
            time.sleep(spec.seconds)
        if k == "corrupt":
            if spec.calls != spec.nth:
                return
            path = info.get("path") or spec.path
            if path:
                spec.fired += 1
                record_fault("injected_faults", f"{site}:corrupt")
                corrupt_file(path)


def fault_point(site, **info):
    """Instrumentation hook: a no-op unless a FaultInjector (context
    manager or env) has a spec for `site`. Keep these on failure-path
    code only — the check is one dict lookup per active injector."""
    for inj in FaultInjector._active():
        inj.fire(site, info)


def corrupt_file(path, magnitude=64):
    """Scribble over the middle of `path` (a file, or the largest file
    under a directory) — the deterministic stand-in for a torn write or
    bit rot. Returns the file actually corrupted."""
    target = path
    if os.path.isdir(path):
        best, best_size = None, -1
        for dirpath, _, filenames in os.walk(path):
            for fn in filenames:
                p = os.path.join(dirpath, fn)
                try:
                    size = os.path.getsize(p)
                except OSError:
                    continue
                if size > best_size:
                    best, best_size = p, size
        if best is None:
            raise FileNotFoundError(f"no file to corrupt under {path}")
        target = best
    size = os.path.getsize(target)
    with open(target, "r+b") as f:
        f.seek(max(0, size // 2 - magnitude // 2))
        f.write(b"\xde\xad\xbe\xef" * max(1, magnitude // 4))
    return target


# ---------------------------------------------------------------------------
# bad-step guard

class EscalationError(RuntimeError):
    """N consecutive bad steps: rollback alone is not converging."""


class IntegrityError(RuntimeError):
    """A restored checkpoint failed checksum verification."""


def _iter_leaves(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _iter_leaves(v)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            yield from _iter_leaves(v)
    elif tree is not None:
        yield tree


def all_finite(tree):
    """True iff every numeric leaf of `tree` (nested dict/list/tuple of
    scalars / numpy / jax arrays) is finite. Non-numeric leaves are
    ignored. This is a host-side check: jax leaves sync to host."""
    for leaf in _iter_leaves(tree):
        try:
            arr = np.asarray(leaf)
        except Exception:  # noqa: BLE001 — non-numeric leaf
            continue
        if arr.dtype.kind not in "fc":
            continue
        if not np.isfinite(arr).all():
            return False
    return True


class BadStepGuard:
    """Non-finite loss/grad sentinel with rollback and escalation.

        guard = BadStepGuard(rollback_fn=restore_last_ckpt)
        for step in ...:
            loss = train_step(...)
            if not guard.check(step, loss):
                continue            # state rolled back; skip this step
            em.tick(step)

    `check` returns True for a good step. On a bad one it records a
    `rollbacks` fault event, invokes `rollback_fn(step)` and returns
    False; after `max_consecutive` bad steps in a row it records an
    `escalations` event and calls `on_escalate(step, n)` — or raises
    EscalationError when no callback is given (an unbounded
    rollback/NaN loop must not spin forever silently).
    """

    def __init__(self, rollback_fn, max_consecutive=3, on_escalate=None,
                 check_grads=True, grad_norm_threshold=None):
        self.rollback_fn = rollback_fn
        self.max_consecutive = max(1, int(max_consecutive))
        self.on_escalate = on_escalate
        self.check_grads = check_grads
        # exploding-but-FINITE steps: a grad norm above this threshold is
        # a bad step even though every value still passes isfinite (the
        # hapi fused train step exposes its per-step global grad norm so
        # this check sees more than the loss)
        self.grad_norm_threshold = (
            float(grad_norm_threshold) if grad_norm_threshold is not None
            else None)
        self.consecutive = 0
        self.total_rollbacks = 0
        self.last_bad_step = None

    def is_bad(self, loss=None, grads=None, grad_norm=None):
        if loss is not None and not all_finite(loss):
            return "non-finite loss"
        if self.check_grads and grads is not None and not all_finite(grads):
            return "non-finite grad"
        if grad_norm is not None:
            try:
                gn = float(np.asarray(grad_norm))
            except Exception:  # noqa: BLE001 — unreadable norm: ignore
                return None
            if not np.isfinite(gn):
                return "non-finite grad norm"
            if self.grad_norm_threshold is not None and \
                    gn > self.grad_norm_threshold:
                return (f"grad norm {gn:.4g} exceeds threshold "
                        f"{self.grad_norm_threshold:.4g}")
        return None

    def check(self, step, loss=None, grads=None, grad_norm=None):
        why = self.is_bad(loss, grads, grad_norm)
        if why is None:
            self.consecutive = 0
            return True
        self.consecutive += 1
        self.total_rollbacks += 1
        self.last_bad_step = step
        record_fault("rollbacks", f"step {step}: {why}")
        warnings.warn(
            f"paddle_tpu resilience: {why} at step {step} — rolling back "
            f"to the last good checkpoint and skipping forward "
            f"({self.consecutive} consecutive)", stacklevel=2)
        if self.rollback_fn is not None:
            self.rollback_fn(step)
        if self.consecutive >= self.max_consecutive:
            record_fault("escalations",
                         f"step {step}: {self.consecutive} consecutive")
            if self.on_escalate is not None:
                self.on_escalate(step, self.consecutive)
            else:
                raise EscalationError(
                    f"{self.consecutive} consecutive bad steps ending at "
                    f"step {step} ({why}); rollback is not converging")
        return False


# ---------------------------------------------------------------------------
# small shared util: atomic json write (heartbeats, integrity manifests)

def atomic_write_json(path, payload, fsync=True):
    """Write JSON then rename, so readers never observe a torn file
    (the same contract orbax gives step directories). `fsync=True`
    makes it durable too (integrity manifests); heartbeats skip the
    fsync — freshness, not durability, is their contract.

    The tmp name is keyed by pid AND thread: a pid-only key let two
    threads of one process (step loop + watchdog, sync + background
    merge) write the same path, rename each other's tmp away, and
    crash with FileNotFoundError — the exact tmp-collision class the
    PR-6 reviews kept hitting."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
