"""Unified telemetry: metrics registry, structured event stream, and
exporters.

Until this module existed the runtime's observability was three
parallel *point-in-time* snapshot dicts — `dispatch_stats()`,
`fault_events()`, and the warm-start compile metrics — readable only by
`profiler.summary` in the live process, with no time axis, no export
path, and no way to correlate a fault event with the step that caused
it. A production jax_graft stack (heavy traffic, long runs, multihost)
needs the telemetry layer TVM-style compiler stacks and the LazyTensor
eager/compiled hybrid both lean on: continuous per-op and per-step
measurements that survive the process and feed dashboards, so a
regression in the dispatch or warm-start layers is caught from the
metrics stream rather than an ad-hoc bench run.

Three pieces, one kill switch (``PADDLE_TPU_TELEMETRY=0`` disables all
ambient collection; explicitly constructed sinks keep working):

* **Metrics registry** — process-wide counters, gauges and fixed-bucket
  histograms, all label-capable and mergeable across processes
  (`merge_histograms`). The hot path is one module-global truthiness
  check plus one uncontended lock acquire; series materialize lazily
  per label set. `sync_runtime_metrics()` mirrors the existing
  authoritative snapshots (`dispatch_stats()`, `fault_events()`,
  compile metrics, HBM stats) into the registry — the snapshots stay
  the single source of truth, the registry is the exported view, so
  the two reconcile *exactly* by construction.

* **Structured event stream** — append-only JSONL, one object per
  event with wall (`ts`) + monotonic (`mono`) timestamps and
  host/pid tags, flushed per record (a ``kill -9`` loses at most the
  line being written) and rotated at a byte bound
  (``PADDLE_TPU_TELEMETRY_EVENTS_MAX_BYTES`` × ``_MAX_FILES``).
  Producers across the stack emit here: fault events
  (runtime/resilience.py), watchdog transitions
  (distributed/elastic.py), checkpoint save/restore durations
  (io/checkpoint.py), compile/disk-cache activity (runtime/warmup.py),
  and per-step training records (`hapi.TelemetryCallback`).

* **Exporters** — Prometheus textfile (`write_prometheus`, atomic
  rename so a node-exporter textfile collector never reads a torn
  file), registry-snapshot JSONL (`append_snapshot_jsonl`, one
  snapshot object per line = a poor man's TSDB), and a
  TensorBoard-consumable per-step scalars sink (`ScalarsSink`, the
  format `hapi.VisualDL` has always written — that callback is now a
  thin wrapper over this sink).

`SCHEMA` names every metric and event kind the stack emits;
tools/telemetry_smoke.py gates it against the checked-in
tools/telemetry_schema.json so a rename is a deliberate, reviewed act
(dashboards key on these names).

Import-weight contract: stdlib only at import time (resilience and
core/dispatch import this module eagerly; jax is only touched inside
`sync_runtime_metrics`/`poll_memory_gauges`, lazily and guarded).
Everything here is host-side control plane and must never run under a
trace — the wall-clock reads are exactly what tracelint TL004 forbids
in op bodies.
"""
from __future__ import annotations

import collections
import json
import os
import socket
import threading
import time

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
    "counter", "gauge", "histogram", "snapshot", "reset_metrics",
    "enabled", "set_enabled",
    "EventStream", "configure", "event_stream", "emit", "events_path",
    "read_events", "set_rank", "get_rank", "set_flight_tap",
    "write_prometheus", "render_prometheus", "parse_prometheus_textfile",
    "append_snapshot_jsonl", "ScalarsSink", "merge_histograms",
    "publish_registry", "merge_cluster",
    "pushgateway_addr", "push_prometheus",
    "otlp_endpoint", "push_otlp",
    "sync_runtime_metrics", "poll_memory_gauges",
    "schema", "SCHEMA_VERSION", "EVENT_KINDS",
    "DEFAULT_BUCKETS", "op_sample_every",
]

SCHEMA_VERSION = 1


def _env_flag(name, default):
    return os.environ.get(name, default).lower() not in ("0", "false", "no")


_enabled = _env_flag("PADDLE_TPU_TELEMETRY", "1")


def enabled():
    return _enabled


# cluster rank tag: when set (env, or coordination layer at cluster
# bring-up) every event record carries it, so N interleaved multihost
# streams stay attributable after a merge
try:
    _rank = int(os.environ["PADDLE_TPU_CLUSTER_RANK"])
except (KeyError, ValueError):
    _rank = None


def set_rank(rank):
    """Tag subsequent events (and default pushgateway grouping) with
    this process's cluster rank. Returns the previous value."""
    global _rank
    prev = _rank
    _rank = None if rank is None else int(rank)
    return prev


def get_rank():
    return _rank


# listeners for runtime kill-switch flips: consumers that latch a value
# derived from enabled() (dispatch's sampling stride) re-arm through
# these rather than paying an enabled() call on their hot path
_enabled_hooks = []


def on_enabled_change(cb):
    _enabled_hooks.append(cb)


def set_enabled(mode):
    """Runtime analogue of the ``PADDLE_TPU_TELEMETRY`` kill switch:
    False turns every metric mutation and `emit()` into a no-op (and,
    via the change hooks, stops the dispatch layer's sampled
    block_until_ready syncs)."""
    global _enabled
    prev = _enabled
    _enabled = bool(mode)
    if prev != _enabled:
        for cb in _enabled_hooks:
            try:
                cb(_enabled)
            except Exception:  # noqa: BLE001 — a bad hook can't block
                pass
    return prev


def op_sample_env_rate():
    """The env-configured sampling stride, ignoring the kill switch."""
    try:
        return max(0, int(os.environ.get("PADDLE_TPU_TELEMETRY_OP_SAMPLE",
                                         "64")))
    except ValueError:
        return 64


def op_sample_every():
    """Per-op run-time attribution rate for the eager dispatch hot path:
    every Nth cached-op execution is timed (``block_until_ready`` on the
    sampled call only). 0 disables sampling; the kill switch zeroes it
    regardless of the env, so a disabled telemetry layer costs the
    dispatch fast path exactly one falsy int check."""
    return op_sample_env_rate() if _enabled else 0


# ---------------------------------------------------------------------------
# metrics

# duration-flavored defaults (seconds): sub-ms eager ops through
# multi-minute restores all land in a real bucket
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class _Series:
    """One (metric, label values) time series. The mutation hot path is
    a module-global enabled check + one uncontended lock acquire."""

    __slots__ = ("_lock", "value", "bucket_counts", "sum", "count",
                 "_bounds")

    def __init__(self, bounds=None):
        self._lock = threading.Lock()
        self.value = 0.0
        self._bounds = bounds
        if bounds is not None:
            self.bucket_counts = [0] * (len(bounds) + 1)  # +Inf tail
            self.sum = 0.0
            self.count = 0

    def inc(self, n=1):
        if not _enabled:
            return
        with self._lock:
            self.value += n

    def dec(self, n=1):
        self.inc(-n)

    def set(self, v):
        if not _enabled:
            return
        with self._lock:
            self.value = float(v)

    def observe(self, v):
        if not _enabled:
            return
        v = float(v)
        bounds = self._bounds
        i = len(bounds)
        for j, b in enumerate(bounds):  # len(bounds) ~ 16: linear is fine
            if v <= b:
                i = j
                break
        with self._lock:
            self.bucket_counts[i] += 1
            self.sum += v
            self.count += 1


class _Metric:
    """A named metric family; `labels(**kv)` materializes/returns the
    series for one label-value combination. A label-less metric IS its
    own default series (inc/set/observe proxy to it)."""

    kind = None

    def __init__(self, name, help="", labelnames=(), buckets=None):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(buckets) if buckets is not None else None
        self._lock = threading.Lock()
        self._series = {}
        if not self.labelnames:
            self._series[()] = _Series(self.buckets)

    def labels(self, *values, **kv):
        if kv:
            # strict: a typo'd label kwarg must raise, not silently
            # aggregate under the value "None" (misattributed series
            # are worse than a crash in a producer)
            if sorted(kv) != sorted(self.labelnames):
                raise ValueError(
                    f"metric {self.name} takes labels {self.labelnames}, "
                    f"got {sorted(kv)}")
            values = tuple(kv[n] for n in self.labelnames)
        key = tuple(str(v) for v in values)
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"metric {self.name} takes labels {self.labelnames}, "
                f"got {key}")
        s = self._series.get(key)
        if s is None:
            with self._lock:
                s = self._series.setdefault(key, _Series(self.buckets))
        return s

    # label-less convenience: the metric proxies its default series
    def inc(self, n=1):
        self._series[()].inc(n)

    def dec(self, n=1):
        self._series[()].dec(n)

    def set(self, v):
        self._series[()].set(v)

    def observe(self, v):
        self._series[()].observe(v)

    def snapshot(self):
        out = {"type": self.kind, "help": self.help,
               "labelnames": list(self.labelnames), "series": []}
        if self.buckets is not None:
            out["buckets"] = list(self.buckets)
        with self._lock:
            items = list(self._series.items())
        for key, s in items:
            with s._lock:
                rec = {"labels": dict(zip(self.labelnames, key))}
                if self.buckets is None:
                    rec["value"] = s.value
                else:
                    rec.update(bucket_counts=list(s.bucket_counts),
                               sum=s.sum, count=s.count)
            out["series"].append(rec)
        return out


class Counter(_Metric):
    kind = "counter"


class Gauge(_Metric):
    kind = "gauge"


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames,
                         buckets=tuple(sorted(buckets)))


class MetricsRegistry:
    """Process-wide named metric families. Registration is idempotent
    for an identical (name, type) pair — producers in different modules
    can all declare the metric they feed — and a type clash raises (two
    subsystems fighting over one name is a bug, not a merge)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _register(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls:
                    raise ValueError(
                        f"metric {name} already registered as "
                        f"{m.kind}, not {cls.kind}")
                if m.labelnames != tuple(labelnames):
                    # a mismatched re-declaration would fail far from
                    # here (KeyError at observe time) or, for buckets,
                    # silently misbucket — clash at the declaration site
                    raise ValueError(
                        f"metric {name} already registered with labels "
                        f"{m.labelnames}, not {tuple(labelnames)}")
                want = kw.get("buckets")
                if want is not None and m.buckets is not None \
                        and tuple(sorted(want)) != m.buckets:
                    raise ValueError(
                        f"metric {name} already registered with buckets "
                        f"{m.buckets}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()):
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=DEFAULT_BUCKETS):
        return self._register(Histogram, name, help, labelnames,
                              buckets=buckets)

    def get(self, name):
        return self._metrics.get(name)

    def snapshot(self):
        """{name: family snapshot} — values, labels, histogram buckets."""
        with self._lock:
            metrics = list(self._metrics.items())
        return {name: m.snapshot() for name, m in metrics}

    def reset(self):
        """Drop every registered family (test isolation)."""
        with self._lock:
            self._metrics.clear()


_REGISTRY = MetricsRegistry()


def registry():
    return _REGISTRY


def counter(name, help="", labelnames=()):
    return _REGISTRY.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()):
    return _REGISTRY.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=DEFAULT_BUCKETS):
    return _REGISTRY.histogram(name, help, labelnames, buckets)


def snapshot():
    return _REGISTRY.snapshot()


# consumers that keep shadow aggregates mirrored against registry
# metrics (the access log's reconciliation surface) register here so a
# test-isolation reset clears BOTH sides of the exactness invariant
_reset_hooks = []


def on_reset(cb):
    _reset_hooks.append(cb)


def reset_metrics():
    _REGISTRY.reset()
    for cb in list(_reset_hooks):
        try:
            cb()
        except Exception:  # noqa: BLE001 — a bad hook can't block reset
            pass


def merge_histograms(snaps):
    """Merge histogram *series snapshots* (same bucket bounds) from
    several processes into one: element-wise bucket sums. This is why
    the buckets are fixed at declaration — mergeability across bench
    children and multihost ranks."""
    out = None
    for s in snaps:
        if out is None:
            out = {"bucket_counts": list(s["bucket_counts"]),
                   "sum": float(s["sum"]), "count": int(s["count"])}
            continue
        if len(s["bucket_counts"]) != len(out["bucket_counts"]):
            raise ValueError("histogram bucket layouts differ; cannot merge")
        out["bucket_counts"] = [a + b for a, b in
                                zip(out["bucket_counts"],
                                    s["bucket_counts"])]
        out["sum"] += float(s["sum"])
        out["count"] += int(s["count"])
    return out or {"bucket_counts": [], "sum": 0.0, "count": 0}


# ---------------------------------------------------------------------------
# structured event stream

def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return int(default)


class EventStream:
    """Append-only JSONL event log with bounded rotation.

    Every record carries wall (`ts`, unix seconds) AND monotonic
    (`mono`) timestamps — wall for cross-host correlation, monotonic
    for durations that survive NTP steps — plus host/pid tags so
    multihost runs can interleave their streams. Writes are flushed
    per record: the PR-3 ``kill -9`` scenario loses at most the line
    in flight, never the run's history. When the active file exceeds
    `max_bytes` it rotates (``events.jsonl`` → ``events.jsonl.1`` →
    ...), keeping `max_files` generations.
    """

    def __init__(self, path, max_bytes=None, max_files=None):
        self.path = path
        self.max_bytes = max_bytes if max_bytes is not None else _env_int(
            "PADDLE_TPU_TELEMETRY_EVENTS_MAX_BYTES", 8 * 1024 * 1024)
        self.max_files = max(1, max_files if max_files is not None else
                             _env_int("PADDLE_TPU_TELEMETRY_EVENTS_MAX_FILES",
                                      3))
        self._lock = threading.Lock()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "a")
        self._host = socket.gethostname()
        self._pid = os.getpid()
        self.emitted = 0

    def emit(self, kind, **fields):
        """Append one event. Never raises into the caller — telemetry
        must not be able to kill the training loop it observes."""
        rec = {"ts": round(time.time(), 6),
               "mono": round(time.monotonic(), 6),
               "host": self._host, "pid": self._pid, "kind": kind}
        if _rank is not None:
            rec["rank"] = _rank
        rec.update(fields)
        try:
            line = json.dumps(rec, default=str) + "\n"
        except (TypeError, ValueError):
            return
        with self._lock:
            try:
                self._f.write(line)  # threadlint: ok[CL003] per-record flush under the lock IS the kill -9 durability contract; writers must serialize
                self._f.flush()  # threadlint: ok[CL003] see above — sub-ms on a local file, and rotation depends on tell() after flush
                self.emitted += 1
                if self.max_bytes and self._f.tell() >= self.max_bytes:
                    self._rotate()
            except (OSError, ValueError):  # closed file / full disk
                pass

    def _rotate(self):
        self._f.close()
        if self.max_files == 1:
            self._f = open(self.path, "w")  # single-file bound: truncate  # threadlint: ok[CL003,CL005] rotation must be atomic w.r.t. writers (caller holds the lock); readers tolerate the truncation by contract (read_events)
            return
        # shift generations up (os.replace clobbers, so the oldest falls
        # off the end), then start a fresh active file
        for i in range(self.max_files - 1, 0, -1):
            src = self.path if i == 1 else f"{self.path}.{i - 1}"
            try:
                os.replace(src, f"{self.path}.{i}")
            except OSError:
                pass
        self._f = open(self.path, "a")  # threadlint: ok[CL003] rotation must swap the file atomically w.r.t. writers — the emit caller holds the lock by design

    def close(self):
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


_stream = None
_stream_lock = threading.Lock()
_config = {"dir": None}


def configure(directory=None, max_bytes=None, max_files=None):
    """Point the global event stream (and default exporter paths) at
    `directory` (default: ``PADDLE_TPU_TELEMETRY_DIR``). Returns the
    effective directory, or None when nowhere is configured. Safe to
    call repeatedly; reconfiguring to a new directory closes the old
    stream."""
    global _stream
    directory = directory or os.environ.get("PADDLE_TPU_TELEMETRY_DIR")
    if not directory:
        return None
    directory = os.path.abspath(directory)
    with _stream_lock:
        if _config["dir"] == directory and _stream is not None:
            # same dir: honor newly requested rotation bounds in place
            # (an early return that dropped them would let the stream
            # grow far past the cap the caller just asked for)
            if max_bytes is not None:
                _stream.max_bytes = int(max_bytes)
            if max_files is not None:
                _stream.max_files = max(1, int(max_files))
            return directory
        # open the NEW stream before touching the old one: a failed
        # reconfigure (unwritable dir) must leave the current stream
        # live, not leave the process silently emitting into a closed
        # file for the rest of the run
        os.makedirs(directory, exist_ok=True)
        new = EventStream(os.path.join(directory, "events.jsonl"),
                          max_bytes=max_bytes, max_files=max_files)
        if _stream is not None:
            _stream.close()
        _stream = new
        _config["dir"] = directory
    return directory


def event_stream():
    return _stream


def telemetry_dir():
    return _config["dir"]


def events_path():
    return _stream.path if _stream is not None else None


# the flight-recorder tap (runtime/diagnostics.py): fn(kind, fields),
# fed from EVERY emit regardless of whether a stream is configured —
# the crash ring must hold recent events even in a process that never
# opted into an event stream. None (one falsy check) when diagnostics
# is absent or killed.
_flight = [None]


def set_flight_tap(fn):
    """Register (or, with None, disarm) the flight-recorder event tap.
    Returns the previous tap."""
    prev = _flight[0]
    _flight[0] = fn  # threadlint: ok[CL001] GIL-atomic publish; config-time single-writer (set_warmup_count contract)
    return prev


def emit(kind, **fields):
    """Emit one structured event to the global stream. A no-op (one
    None/flag check) when no stream is configured or the kill switch is
    off — producers across the stack call this unconditionally. The
    flight-recorder tap (when armed) sees every event first, stream or
    no stream."""
    tap = _flight[0]
    if tap is not None:
        tap(kind, fields)
    if _stream is None or not _enabled:
        return
    _stream.emit(kind, **fields)


def read_events(path=None, include_rotated=True):
    """Parse events back (oldest first, rotated generations included).
    Tolerates a torn final line — the kill -9 contract."""
    path = path or events_path()
    if path is None:
        return []
    paths = []
    if include_rotated:
        i = 1
        while os.path.exists(f"{path}.{i}"):
            paths.append(f"{path}.{i}")
            i += 1
        paths.reverse()
    paths.append(path)
    out = []
    for p in paths:
        try:
            with open(p) as f:
                for line in f:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue  # torn tail line
        except OSError:
            continue
    return out


# ---------------------------------------------------------------------------
# exporters

def _escape_label(v):
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def _fmt_labels(labels, extra=()):
    items = list(labels.items()) + list(extra)
    if not items:
        return ""
    return ("{" + ",".join(f'{k}="{_escape_label(v)}"' for k, v in items)
            + "}")


def _fmt_value(v):
    v = float(v)
    if v != v:
        return "NaN"  # prom exposition spelling; float("NaN") parses back
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def render_prometheus(snap=None):
    """The registry (or a snapshot) as Prometheus text exposition
    format — shared by the textfile writer and the pushgateway
    exporter."""
    snap = snap if snap is not None else _REGISTRY.snapshot()
    lines = []
    for name in sorted(snap):
        fam = snap[name]
        if fam["help"]:
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for s in fam["series"]:
            labels = s["labels"]
            if fam["type"] == "histogram":
                acc = 0
                for bound, n in zip(fam["buckets"], s["bucket_counts"]):
                    acc += n
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(labels, [('le', repr(float(bound)))])}"
                        f" {acc}")
                acc += s["bucket_counts"][-1]
                lines.append(
                    f"{name}_bucket{_fmt_labels(labels, [('le', '+Inf')])}"
                    f" {acc}")
                lines.append(
                    f"{name}_sum{_fmt_labels(labels)} "
                    f"{_fmt_value(s['sum'])}")
                lines.append(
                    f"{name}_count{_fmt_labels(labels)} {s['count']}")
            else:
                lines.append(
                    f"{name}{_fmt_labels(labels)} {_fmt_value(s['value'])}")
    return "\n".join(lines) + "\n"


def write_prometheus(path=None, snap=None):
    """Render the registry in Prometheus text exposition format and
    write it atomically (tmp + rename — the node-exporter textfile
    collector convention, so a scraper never reads a torn file).
    Default path: ``<telemetry dir>/metrics.prom``. Returns the path
    written, or None when there is nowhere to write."""
    if path is None:
        d = _config["dir"]
        if d is None:
            return None
        path = os.path.join(d, "metrics.prom")
    text = render_prometheus(snap)
    # pid AND thread keyed: the background merge thread and a train-end
    # synchronous writer must never share a tmp path
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# pushgateway exporter (opt-in): multihost ranks push straight to a
# Prometheus pushgateway instead of riding the textfile-collector hop

def pushgateway_addr():
    """``PADDLE_TPU_TELEMETRY_PUSHGATEWAY`` as ``host:port``, or None
    (the exporter is strictly opt-in)."""
    return os.environ.get("PADDLE_TPU_TELEMETRY_PUSHGATEWAY") or None


def push_prometheus(addr=None, snap=None, job="paddle_tpu", instance=None,
                    timeout=2.0):
    """PUT the registry (or `snap`) to a Prometheus pushgateway at
    ``http://<addr>/metrics/job/<job>/instance/<instance>``.

    `instance` defaults to ``rank<r>`` in cluster mode, else
    ``<host>:<pid>`` — each rank groups under its own instance so
    pushes never clobber a peer's series. Returns True on an accepted
    push. EVERY failure path (no listener, refused connection, HTTP
    error, timeout) degrades to a warning + `push_failures` fault
    event and returns False — a dead pushgateway must never raise into
    the training loop that is pushing to it."""
    addr = addr or pushgateway_addr()
    if addr is None:
        return False
    if instance is None:
        instance = (f"rank{_rank}" if _rank is not None
                    else f"{socket.gethostname()}:{os.getpid()}")
    try:
        import http.client

        host, _, port = addr.partition(":")
        body = render_prometheus(snap).encode()
        conn = http.client.HTTPConnection(host, int(port or 9091),
                                          timeout=float(timeout))
        try:
            conn.request("PUT", f"/metrics/job/{job}/instance/{instance}",
                         body=body,
                         headers={"Content-Type": "text/plain"})
            resp = conn.getresponse()
            resp.read()
            status = resp.status
        finally:
            conn.close()
        if status >= 300:
            raise OSError(f"pushgateway returned HTTP {status}")
    except Exception as e:  # noqa: BLE001 — degrade, never raise into fit
        from .resilience import record_fault  # lazy: no import cycle

        record_fault("push_failures",
                     f"{addr}: {type(e).__name__}: {e}")
        import warnings

        warnings.warn(
            f"paddle_tpu telemetry: pushgateway push to {addr} failed "
            f"({type(e).__name__}: {e}) — metrics dropped for this "
            "interval, training continues", stacklevel=2)
        return False
    return True


# ---------------------------------------------------------------------------
# OTLP exporter (opt-in): OTLP/HTTP JSON to any OpenTelemetry collector,
# stdlib only — the carried ROADMAP follow-up next to the pushgateway

def otlp_endpoint():
    """``PADDLE_TPU_TELEMETRY_OTLP`` as a collector base URL (e.g.
    ``http://collector:4318`` or ``collector:4318``), or None (the
    exporter is strictly opt-in)."""
    return os.environ.get("PADDLE_TPU_TELEMETRY_OTLP") or None


def _otlp_attrs(labels):
    return [{"key": k, "value": {"stringValue": str(v)}}
            for k, v in labels.items()]


# cumulative-series start timestamp: collectors use it for reset
# detection across process restarts (a restarted trainer's counters
# drop to ~0; without a start time a rate pipeline misreads that as a
# negative delta). Process start is the registry's effective epoch.
_OTLP_START_NS = int(time.time() * 1e9)


def _otlp_payload(snap, now_ns=None):
    """An ExportMetricsServiceRequest (OTLP/HTTP JSON encoding) from a
    registry snapshot: counters -> monotonic cumulative sums, gauges ->
    gauges, histograms -> cumulative explicit-bounds histograms. Int64
    fields are strings per the OTLP JSON mapping."""
    now_ns = now_ns if now_ns is not None else int(time.time() * 1e9)
    metrics = []
    for name in sorted(snap):
        fam = snap[name]
        if fam["type"] == "histogram":
            dps = []
            for s in fam["series"]:
                dps.append({
                    "startTimeUnixNano": str(_OTLP_START_NS),
                    "timeUnixNano": str(now_ns),
                    "count": str(int(s["count"])),
                    "sum": float(s["sum"]),
                    "bucketCounts": [str(int(c))
                                     for c in s["bucket_counts"]],
                    "explicitBounds": [float(b) for b in fam["buckets"]],
                    "attributes": _otlp_attrs(s["labels"]),
                })
            metrics.append({"name": name,
                            "description": fam.get("help", ""),
                            "histogram": {"dataPoints": dps,
                                          "aggregationTemporality": 2}})
            continue
        dps = [{"timeUnixNano": str(now_ns),
                "asDouble": float(s["value"]),
                "attributes": _otlp_attrs(s["labels"])}
               for s in fam["series"]]
        if fam["type"] == "counter":
            for dp in dps:
                dp["startTimeUnixNano"] = str(_OTLP_START_NS)
            metrics.append({"name": name,
                            "description": fam.get("help", ""),
                            "sum": {"dataPoints": dps,
                                    "aggregationTemporality": 2,
                                    "isMonotonic": True}})
        else:
            metrics.append({"name": name,
                            "description": fam.get("help", ""),
                            "gauge": {"dataPoints": dps}})
    resource = [{"key": "service.name",
                 "value": {"stringValue": "paddle_tpu"}},
                {"key": "host.name",
                 "value": {"stringValue": socket.gethostname()}}]
    if _rank is not None:
        resource.append({"key": "paddle_tpu.rank",
                         "value": {"stringValue": str(_rank)}})
    return {"resourceMetrics": [{
        "resource": {"attributes": resource},
        "scopeMetrics": [{"scope": {"name": "paddle_tpu.telemetry"},
                          "metrics": metrics}]}]}


def push_otlp(endpoint=None, snap=None, timeout=2.0):
    """POST the registry (or `snap`) to an OTLP/HTTP collector at
    ``<endpoint>/v1/metrics`` as OTLP JSON. Returns True on an
    accepted export. EVERY failure path (no listener, HTTP error,
    timeout, bad endpoint) degrades to a warning + `push_failures`
    fault event and returns False — a dead collector must never raise
    into the training loop, the same contract as the pushgateway."""
    endpoint = endpoint or otlp_endpoint()
    if endpoint is None:
        return False
    try:
        import http.client
        import urllib.parse

        if "//" not in endpoint:
            endpoint = "http://" + endpoint
        u = urllib.parse.urlsplit(endpoint)
        path = u.path.rstrip("/")
        if not path.endswith("/v1/metrics"):
            path += "/v1/metrics"
        body = json.dumps(_otlp_payload(
            snap if snap is not None else _REGISTRY.snapshot())).encode()
        cls = (http.client.HTTPSConnection if u.scheme == "https"
               else http.client.HTTPConnection)
        conn = cls(u.hostname,
                   u.port or (443 if u.scheme == "https" else 4318),
                   timeout=float(timeout))
        try:
            conn.request("POST", path, body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            status = resp.status
        finally:
            conn.close()
        if status >= 300:
            raise OSError(f"OTLP collector returned HTTP {status}")
    except Exception as e:  # noqa: BLE001 — degrade, never raise into fit
        from .resilience import record_fault  # lazy: no import cycle

        record_fault("push_failures",
                     f"otlp {endpoint}: {type(e).__name__}: {e}")
        import warnings

        warnings.warn(
            f"paddle_tpu telemetry: OTLP export to {endpoint} failed "
            f"({type(e).__name__}: {e}) — metrics dropped for this "
            "interval, training continues", stacklevel=2)
        return False
    return True


# ---------------------------------------------------------------------------
# cross-host aggregation: per-rank publication + host-0 merge

MERGE_STATE_BASENAME = "merge_state.json"
MERGE_STATE_VERSION = 1
# per-rank bound on accumulated stream fault records carried in the
# merge state (the merged faults.jsonl is rebuilt from this state each
# boundary; an unbounded run must not grow it without limit)
_MERGE_FAULTS_CAP = 10000


def _tail_jsonl(path, offset):
    """Parse complete JSON lines from byte `offset` of a JSONL file.
    Returns (records, new_offset); a torn final line (no trailing
    newline yet) is left in place for the next tail."""
    try:
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read()
    except OSError:
        return [], offset
    end = data.rfind(b"\n")
    if end < 0:
        return [], offset
    records = []
    for line in data[:end].split(b"\n"):
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            continue
    return records, offset + end + 1


def _load_merge_state(out_dir, key="ranks"):
    if not out_dir:
        return {}
    try:
        with open(os.path.join(out_dir, MERGE_STATE_BASENAME)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    if doc.get("version") != MERGE_STATE_VERSION:
        return {}
    sub = doc.get(key)
    return dict(sub) if isinstance(sub, dict) else {}


def _head_signature(path):
    """Hash of the file's FIRST LINE (capped at 256 bytes) — stable
    across appends, changed by truncation/replacement even when the new
    file is LONGER than the old offset (size alone cannot tell a
    fast-growing fresh incarnation from more appends). A still-torn
    first line hashes differently once it completes; the resulting
    one-off reset is dedup-safe."""
    import hashlib

    try:
        with open(path, "rb") as f:
            head = f.read(257)
    except OSError:
        return ""
    if not head:
        return ""
    line = head.partition(b"\n")[0][:256]
    return hashlib.sha1(line).hexdigest()


def _tail_rank_events(path, st, rank):
    """Advance one rank's tail state `st` ({offset, head, starts,
    faults}) by the event-stream bytes written since the last merge —
    O(new bytes), not O(run length). The tail resets to 0 (re-scanning
    the ``.1`` generation, with exact duplicates deduped against the
    accumulated state) when the incarnation changed under us: the file
    shrank below the saved offset, OR its head signature changed — a
    relaunched rank's fresh file can grow PAST the old offset between
    merges, and a mid-file seek into the new incarnation would silently
    drop its earliest fault records."""
    try:
        size = os.path.getsize(path)
    except OSError:
        size = 0
    offset = int(st.get("offset", 0))
    head = _head_signature(path)
    fresh = offset == 0
    if size < offset or (offset > 0 and head != st.get("head")):
        offset = 0
        fresh = True
    st["head"] = head
    new_records = []
    if fresh:
        # rotated generations are read once per (re)start of the tail;
        # steady-state merges touch only the active file's new bytes
        gens = []
        i = 1
        while os.path.exists(f"{path}.{i}"):
            gens.append(f"{path}.{i}")
            i += 1
        for p in reversed(gens):
            recs, _ = _tail_jsonl(p, 0)
            new_records.extend(recs)
    tail, offset = _tail_jsonl(path, offset)
    new_records.extend(tail)

    starts = st.setdefault("starts", {})
    faults = st.setdefault("faults", [])
    seen = {(r.get("ts"), r.get("fault"), r.get("detail"), r.get("pid"))
            for r in faults}
    for ev in new_records:
        ts = ev.get("ts")
        if isinstance(ts, (int, float)):
            pid = str(ev.get("pid"))
            prev = starts.get(pid)
            if prev is None or ts < prev:
                starts[pid] = ts
        if ev.get("kind") != "fault":
            continue
        rec = {"ts": ev.get("ts"), "fault": ev.get("fault"),
               "detail": ev.get("detail"), "rank": ev.get("rank", rank),
               "pid": ev.get("pid"), "source": "events"}
        key = (rec["ts"], rec["fault"], rec["detail"], rec["pid"])
        if key in seen:
            continue
        seen.add(key)
        faults.append(rec)
    if len(faults) > _MERGE_FAULTS_CAP:
        del faults[:len(faults) - _MERGE_FAULTS_CAP]
    st["offset"] = offset
    return st


def _trace_sources(root):
    """Per-process Chrome trace files to merge: every ``trace-*.json``
    under ``<store root>/traces/`` (the cluster default — ranks point
    ``PADDLE_TPU_TRACE`` at a shared dir under the store), plus — best
    effort — this host's own configured trace dir wherever it lives
    (it may be a store subdir other than ``traces/``, or a local dir
    in a single-host multi-process cluster). A rank tracing to a local
    dir on a DEAD host is unreachable from host 0; the merged timeline
    then covers that rank only up to what it wrote into the store, the
    same visibility trade-off init_cluster_telemetry warns about for
    the event stream."""
    roots = []
    if root:
        roots.append(os.path.join(root, "traces"))
    from . import tracing as _tracing  # lazy: tracing imports telemetry

    td = _tracing.trace_dir()
    if td:
        roots.append(td)
    out, seen = [], set()
    for r in roots:
        for dirpath, _dirs, files in os.walk(r):
            for fn in sorted(files):
                if fn.startswith(_tracing.TRACE_BASENAME_PREFIX) and \
                        fn.endswith(".json"):
                    p = os.path.abspath(os.path.join(dirpath, fn))
                    if p not in seen:
                        seen.add(p)
                        out.append(p)
    return out


def _trace_head_signature(path):
    """Incarnation signature for a Chrome trace file. The first line of
    EVERY trace file is the identical ``[`` array opener, so (unlike
    the event streams) the first-line hash cannot tell two files apart
    — hash the SECOND line instead: the first buffered record, the
    process metadata whose os_pid differs per incarnation. Returns ""
    until that line is complete — which is also before any record line
    exists, so an empty->nonempty transition can only reset a tail
    that had consumed nothing but the opener."""
    import hashlib

    try:
        with open(path, "rb") as f:
            head = f.read(1024)
    except OSError:
        return ""
    rest = head.partition(b"\n")[2]
    line, nl, _ = rest.partition(b"\n")
    if not nl:
        return ""
    return hashlib.sha1(line[:512]).hexdigest()


def _merge_trace_files(sources, out_path, state):
    """Tail each per-process trace file from its persisted byte offset
    (the PR-8 event-stream pattern: O(new bytes) per boundary, offset
    reset on relaunch/truncation via the head signature) and append the
    complete events to ONE merged Chrome trace at `out_path`. Every
    event already carries its rank as ``pid`` (the tracer lanes on the
    cluster rank), so the merged file IS the cluster timeline. Returns
    the number of events appended."""
    lines_out = []
    for path in sources:
        key = "/".join(path.replace(os.sep, "/").rsplit("/", 2)[-2:])
        st = state.get(key)
        if not isinstance(st, dict):
            st = {}
        try:
            size = os.path.getsize(path)
        except OSError:
            continue
        offset = int(st.get("offset", 0))
        head = _trace_head_signature(path)
        if size < offset or (offset > 0 and head != st.get("head")):
            # relaunched incarnation writes a NEW file name (pid-keyed),
            # so a reset here means the same path was truncated/replaced
            # (pid recycling) — re-tail from 0; span events are
            # append-only so the worst case is a duplicated prefix in
            # the merged view
            offset = 0
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                data = f.read()
        except OSError:
            continue
        end = data.rfind(b"\n")
        if end < 0:
            continue
        for raw in data[:end].split(b"\n"):
            s = raw.strip().rstrip(b",")
            if not s or s in (b"[", b"]"):
                continue
            if s.endswith(b"]"):  # the "{}]"-style terminator line
                s = s[:-1].rstrip().rstrip(b",")
                if not s:
                    continue
            try:
                ev = json.loads(s)
            except ValueError:
                continue
            if ev:  # drop the {} comma pad
                lines_out.append(json.dumps(ev, default=str) + ",\n")
        state[key] = {"offset": offset + end + 1, "head": head}
    if not lines_out and os.path.exists(out_path):
        return 0
    fresh = not os.path.exists(out_path) or os.path.getsize(out_path) == 0
    with open(out_path, "a") as f:
        if fresh:
            f.write("[\n")
        f.write("".join(lines_out))
        f.flush()
    return len(lines_out)


def publish_registry(store, rank=None, extra=None):
    """Publish this rank's full telemetry view — registry snapshot,
    fault-event counters, and the bounded fault log — into a
    coordination store under ``telemetry/rank_<r>``. Ranks publish at
    checkpoint boundaries; host 0 runs `merge_cluster` over the
    publications."""
    from .resilience import fault_events, fault_log  # lazy: no cycle

    rank = rank if rank is not None else (_rank or 0)
    payload = {"rank": int(rank), "wall": round(time.time(), 6),
               "host": socket.gethostname(), "pid": os.getpid(),
               "metrics": _REGISTRY.snapshot(),
               "fault_events": fault_events(),
               "fault_log": [{"ts": round(ts, 6), "fault": kind,
                              "detail": detail}
                             for ts, kind, detail in fault_log(last=256)]}
    if extra:
        payload.update(extra)
    store.put(f"telemetry/rank_{int(rank)}", payload)
    return payload


def _merge_rank_snapshots(ranks_snaps):
    """One combined registry snapshot from {rank: snapshot}: every
    series gains a ``rank`` label, and histograms additionally get a
    ``rank="all"`` series merged across ranks (mergeable fixed buckets
    are why Histogram bounds are frozen at declaration)."""
    merged = {}
    for rank in sorted(ranks_snaps):
        for name, fam in ranks_snaps[rank].items():
            out = merged.get(name)
            if out is None:
                out = {"type": fam["type"], "help": fam.get("help", ""),
                       "labelnames": list(fam.get("labelnames", ()))
                       + ["rank"], "series": []}
                if "buckets" in fam:
                    out["buckets"] = list(fam["buckets"])
                merged[name] = out
            for s in fam["series"]:
                rec = dict(s)
                rec["labels"] = {**s["labels"], "rank": str(rank)}
                out["series"].append(rec)
    # histogram aggregates: group each family's series by their
    # original (rank-less) labels and merge bucket counts
    for name, fam in merged.items():
        if fam["type"] != "histogram":
            continue
        groups = {}
        for s in fam["series"]:
            base = tuple(sorted((k, v) for k, v in s["labels"].items()
                                if k != "rank"))
            groups.setdefault(base, []).append(s)
        for base, series in groups.items():
            agg = merge_histograms(series)
            agg["labels"] = {**dict(base), "rank": "all"}
            fam["series"].append(agg)
    return merged


def merge_cluster(store, out_dir=None, push=False):
    """Host-0 aggregation: read every rank's `publish_registry`
    publication (plus, for directory stores, every per-rank event
    stream under ``events/rank_<r>/``), and produce ONE view of the
    whole job:

    * ``<out_dir>/cluster.prom`` — a Prometheus textfile whose every
      series carries a ``rank`` label (histograms gain a merged
      ``rank="all"`` aggregate);
    * ``<out_dir>/faults.jsonl`` — the cluster-wide fault log, all
      ranks interleaved by wall time, each record rank-tagged. Fault
      events a killed rank flushed to its event stream in its final
      instant (the per-record-flush contract) are included even though
      that rank never published again.

    `out_dir` defaults to ``<store root>/merged``. With `push=True`
    (or rather: whenever a pushgateway is configured and push is
    requested) the merged snapshot is also pushed under
    ``instance="cluster"``. Returns a summary dict; never raises into
    the caller (a merge failure is observability lost, not a training
    failure).

    Event streams are TAILED, not re-read: ``<out_dir>/merge_state.json``
    persists, per rank, the active file's byte offset, the per-pid
    incarnation stream starts, and the accumulated stream fault
    records (bounded), so each checkpoint boundary costs O(new bytes)
    instead of O(run length) per rank — the difference between a
    per-interval merge and a stalled leader on slow shared
    filesystems. A relaunched rank (file shorter than the saved
    offset) resets its tail to 0 and re-scans; exact-duplicate records
    are deduped against the accumulated state.

    Known limitation: a fault recorded while the ``PADDLE_TPU_TELEMETRY``
    kill switch was OFF (emit no-ops, so it exists only in the
    publication fault_log) is indistinguishable from a stream
    duplicate once the rank's stream has earlier records, and the
    stream-supersedes dedup drops it — disabling telemetry accepts
    holes in telemetry-derived artifacts."""
    ranks_snaps, fault_recs, ranks = {}, [], []
    for key in store.list("telemetry"):
        pub = store.get(key)
        if not isinstance(pub, dict) or "rank" not in pub:
            continue
        rank = int(pub["rank"])
        ranks.append(rank)
        if isinstance(pub.get("metrics"), dict):
            ranks_snaps[rank] = pub["metrics"]
        for f in pub.get("fault_log") or ():
            fault_recs.append({**f, "rank": rank, "source": "publication",
                               "pid": pub.get("pid")})
    # per-rank event streams (directory stores): catches the fault a
    # dying rank flushed after its last publication. Tailed from saved
    # byte offsets (persisted per rank in <out_dir>/merge_state.json,
    # with per-pid incarnation stream starts) so each boundary reads
    # O(new bytes), not the whole file again — the whole-file re-read
    # was O(run length x ranks) per checkpoint interval on slow shared
    # filesystems (ROADMAP PR-6 follow-up)
    root = getattr(store, "root", None)
    if out_dir is None and root is not None:
        out_dir = os.path.join(root, "merged")
    state_ranks = _load_merge_state(out_dir)
    trace_state = _load_merge_state(out_dir, "traces")
    if root:
        events_root = os.path.join(root, "events")
        try:
            rank_dirs = sorted(os.listdir(events_root))
        except OSError:
            rank_dirs = []
    # (rank, pid) -> earliest ts across that INCARNATION's stream
    # events: a reused store dir holds the previous incarnation's
    # stream too, and its earlier timestamps must not bound (and so
    # swallow) a relaunched process's pre-stream publication faults
    stream_start = {}
    if root:
        for d in rank_dirs:
            if not d.startswith("rank_"):
                continue
            try:
                rank = int(d[len("rank_"):])
            except ValueError:
                continue
            st = state_ranks.get(str(rank))
            if not isinstance(st, dict):
                st = {}
            st = _tail_rank_events(
                os.path.join(events_root, d, "events.jsonl"), st, rank)
            state_ranks[str(rank)] = st
            for pid, ts in st.get("starts", {}).items():
                # starts keys are str(pid) (JSON round trip); records
                # with no pid tag persist as "None" and must keep
                # matching pid-less publication records
                try:
                    key = (rank, None if pid == "None" else int(pid))
                except (TypeError, ValueError):
                    continue
                prev = stream_start.get(key)
                if prev is None or ts < prev:
                    stream_start[key] = ts
            fault_recs.extend(dict(r) for r in st.get("faults", ()))
    # a fault recorded while the stream was live exists in BOTH sources
    # (record_fault's log entry and the emit), with timestamps differing
    # by the microseconds between the two time.time() calls — so
    # per-record keys can never match them up. Drop a publication
    # record only from the rank's stream start onward; faults recorded
    # BEFORE the stream was configured (warm-start/import faults ahead
    # of cluster bring-up) exist only in the publication and must
    # survive. The 10ms slack covers the record-vs-emit timestamp gap
    # of a fault that IS the rank's first stream event.
    def _dup(r):
        start = stream_start.get((r["rank"], r.get("pid")))
        return (r["source"] != "events" and start is not None
                and (r.get("ts") or 0.0) >= start - 0.01)

    fault_recs = [r for r in fault_recs if not _dup(r)]
    fault_recs.sort(key=lambda r: (r.get("ts") or 0.0, r["rank"]))
    out = {"ranks": sorted(set(ranks)), "fault_count": len(fault_recs),
           "prom_path": None, "faults_path": None, "snapshot": {},
           "faults": fault_recs, "trace_path": None, "trace_events": 0}
    try:
        # inside the guard: ranks running skewed versions can publish
        # incompatible snapshots (histogram bucket layouts differ →
        # merge_histograms raises), and this function promises callers
        # a degraded summary, never an exception
        merged = _merge_rank_snapshots(ranks_snaps)
        out["snapshot"] = merged
        if out_dir is None:
            raise OSError("no out_dir and store has no root directory")
        os.makedirs(out_dir, exist_ok=True)
        out["prom_path"] = write_prometheus(
            os.path.join(out_dir, "cluster.prom"), snap=merged)
        faults_path = os.path.join(out_dir, "faults.jsonl")
        tmp = f"{faults_path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            for r in fault_recs:
                f.write(json.dumps(r, default=str) + "\n")
        os.replace(tmp, faults_path)
        out["faults_path"] = faults_path
        # span-trace merge: ONE Perfetto-loadable cluster timeline from
        # the per-process trace files (byte-offset tailed like the
        # event streams — O(new bytes) per checkpoint boundary). Every
        # event lanes on its rank (the tracer writes pid=rank), so a
        # multihost stall reads as overlapping spans, not counters.
        from . import tracing as _tracing  # lazy: tracing imports us

        _tracing.flush()  # host-0's own unflushed spans must be tailable
        trace_sources = _trace_sources(root)
        if trace_sources:
            tpath = os.path.join(out_dir, "cluster_trace.json")
            n_tr = _merge_trace_files(trace_sources, tpath, trace_state)
            out["trace_path"] = tpath
            out["trace_events"] = n_tr
            emit("trace_merge", files=len(trace_sources), events=n_tr)
        if root or trace_state:
            # persist the tail state AFTER the outputs landed: a merge
            # that dies mid-write re-tails from the previous offsets
            # next time. For FAULT records the exact-duplicate dedup
            # absorbs the overlap; the append-only trace merge has no
            # dedup, so a crashed merge can duplicate spans in the
            # cluster timeline — identical spans overlay invisibly in
            # Perfetto, a far better failure than the reverse ordering
            # (offsets past unwritten data = spans silently LOST)
            spath = os.path.join(out_dir, MERGE_STATE_BASENAME)
            stmp = f"{spath}.tmp.{os.getpid()}.{threading.get_ident()}"
            with open(stmp, "w") as f:
                json.dump({"version": MERGE_STATE_VERSION,
                           "ranks": state_ranks,
                           "traces": trace_state}, f)
            os.replace(stmp, spath)
        if push:
            push_prometheus(snap=merged, instance="cluster")
        emit("cluster_merge", ranks=out["ranks"],
             fault_count=len(fault_recs), prom_path=out["prom_path"])
    except Exception as e:  # noqa: BLE001 — observability lost, not a crash
        import warnings

        warnings.warn(f"paddle_tpu telemetry: cluster merge write failed "
                      f"({type(e).__name__}: {e})", stacklevel=2)
    return out


_PROM_LINE = None  # compiled lazily (stdlib re, parse path only)


def parse_prometheus_textfile(path):
    """Parse a Prometheus textfile back into
    ``{(name, (sorted label items)): value}`` — the round-trip check
    tests and tools/telemetry_smoke.py reconcile against. Histogram
    sample lines parse as their exposition names (``*_bucket`` with an
    ``le`` label, ``*_sum``, ``*_count``)."""
    global _PROM_LINE
    import re

    if _PROM_LINE is None:
        _PROM_LINE = re.compile(
            r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$')
    out = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            m = _PROM_LINE.match(line)
            if not m:
                continue
            name, raw_labels, val = m.groups()
            labels = []
            if raw_labels:
                unesc = {'"': '"', "\\": "\\", "n": "\n"}
                for k, v in re.findall(
                        r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"',
                        raw_labels):
                    # left-to-right unescape (sequential str.replace
                    # corrupts a literal backslash followed by 'n')
                    labels.append((k, re.sub(
                        r'\\(["\\n])',
                        lambda m2: unesc[m2.group(1)], v)))
            out[(name, tuple(sorted(labels)))] = float(val)
    return out


def append_snapshot_jsonl(path=None, extra=None):
    """Append one full registry snapshot (plus wall/mono timestamps) as
    a JSON line — a dashboardable time series of process metrics.
    Default path: ``<telemetry dir>/metrics.jsonl``."""
    if path is None:
        d = _config["dir"]
        if d is None:
            return None
        path = os.path.join(d, "metrics.jsonl")
    rec = {"ts": round(time.time(), 6), "mono": round(time.monotonic(), 6),
           "metrics": _REGISTRY.snapshot()}
    if extra:
        rec.update(extra)
    with open(path, "a") as f:
        f.write(json.dumps(rec, default=str) + "\n")
        f.flush()
    return path


class ScalarsSink:
    """Per-step scalars as JSONL — the TensorBoard-importable format
    `hapi.VisualDL` has always produced (one object per step, float
    values + ``global_step``), now flushed per write so a ``kill -9``
    mid-run keeps every completed step on disk. Explicitly constructed
    sinks write regardless of the kill switch: the user asked for this
    file by name."""

    def __init__(self, log_dir, filename="scalars.jsonl"):
        os.makedirs(log_dir, exist_ok=True)
        self.path = os.path.join(log_dir, filename)
        self._f = open(self.path, "a")
        self._lock = threading.Lock()

    def write(self, step, scalars):
        """Append one step record; non-finite/non-numeric values are the
        caller's problem to filter (floats pass through json as-is)."""
        rec = dict(scalars)
        rec["global_step"] = int(step)
        with self._lock:
            try:
                self._f.write(json.dumps(rec) + "\n")  # threadlint: ok[CL003] per-step flush under the lock is the sink's crash-durability contract
                self._f.flush()  # threadlint: ok[CL003] see above
            except (OSError, ValueError):
                pass

    def close(self):
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# runtime bridge: mirror the authoritative snapshots into the registry

def sync_runtime_metrics():
    """Pull the runtime's authoritative snapshot dicts into the registry
    so every exporter sees one coherent view: dispatch cache counters
    (global + per-op), unjittable demotions, warm-start compile
    counters, time-to-first-step, fault events, and device memory.

    Mirrors are SET to the snapshot value (not incremented): the
    snapshots remain the single source of truth and the registry
    reconciles with them exactly at every sync — the acceptance
    property tools/telemetry_smoke.py asserts. Returns the
    dispatch_stats() snapshot it mirrored (callers often want it)."""
    from ..core.dispatch import dispatch_stats

    ds = dispatch_stats()
    c_hits = counter("paddle_tpu_dispatch_cache_hits_total",
                     "jit-cache hits", ("cache",))
    c_miss = counter("paddle_tpu_dispatch_cache_misses_total",
                     "jit-cache misses", ("cache",))
    c_evic = counter("paddle_tpu_dispatch_cache_evictions_total",
                     "jit-cache LRU evictions", ("cache",))
    g_size = gauge("paddle_tpu_dispatch_cache_size",
                   "live compiled programs", ("cache",))
    fus = ds.get("fusion") or {}
    for which in ("forward", "backward", "fused"):
        # "fused" = the trace-fusion program cache (core/fusion.py),
        # exported as a third label value of the same cache families
        s = fus.get("fused") if which == "fused" else ds[which]
        if not s:
            continue
        c_hits.labels(cache=which).set(s["hits"])
        c_miss.labels(cache=which).set(s["misses"])
        c_evic.labels(cache=which).set(s["evictions"])
        g_size.labels(cache=which).set(s["size"])
    if fus:
        c_fl = counter("paddle_tpu_fusion_flushes_total",
                       "fusion trace flushes", ("reason",))
        for reason, n in (fus.get("flushes") or {}).items():
            c_fl.labels(reason=reason).set(n)
        # flush-site attribution (fuselint --verify-runtime's runtime
        # half): per (reason, forcing code site) counts; the per-reason
        # sums reconcile exactly with paddle_tpu_fusion_flushes_total
        # by construction (core/fusion.py bounds sites per reason and
        # folds overflow into "<other>")
        c_site = counter("paddle_tpu_fusion_flush_reason_total",
                         "fusion flushes attributed to the code site "
                         "that forced them", ("reason", "site"))
        for reason, sites in (fus.get("flush_sites") or {}).items():
            for site, n in sites.items():
                c_site.labels(reason=reason, site=site).set(n)
        counter("paddle_tpu_fusion_recorded_ops_total",
                "eager ops deferred into fusion traces").set(
            fus.get("recorded_ops", 0))
        counter("paddle_tpu_fusion_flushed_ops_total",
                "deferred ops that reached a flush").set(
            fus.get("flushed_ops", 0))
    fwd = ds["forward"]
    for key, mname in (
            ("bypasses", "paddle_tpu_dispatch_bypasses_total"),
            ("unkeyable", "paddle_tpu_dispatch_unkeyable_total"),
            ("fallbacks", "paddle_tpu_dispatch_fallbacks_total"),
            ("warming", "paddle_tpu_dispatch_warming_total"),
            ("manifest_preloads",
             "paddle_tpu_dispatch_manifest_preloads_total")):
        counter(mname, f"forward dispatch {key}").set(fwd[key])
    op_h = counter("paddle_tpu_op_hits_total", "per-op cache hits", ("op",))
    op_m = counter("paddle_tpu_op_misses_total", "per-op cache misses",
                   ("op",))
    op_r = counter("paddle_tpu_op_retraces_total", "per-op retraces",
                   ("op",))
    op_c = counter("paddle_tpu_op_compile_seconds_total",
                   "per-op XLA compile seconds", ("op",))
    for op, s in ds["per_op"].items():
        op_h.labels(op=op).set(s["hits"])
        op_m.labels(op=op).set(s["misses"])
        op_r.labels(op=op).set(s["retraces"])
        if s.get("compile_s"):
            op_c.labels(op=op).set(s["compile_s"])
    uj = ds.get("unjittable") or {}
    g_uj = gauge("paddle_tpu_unjittable_ops",
                 "ops demoted to plain eager", ("source",))
    for src in ("decorated", "manifest_preloaded", "runtime_learned"):
        g_uj.labels(source=src).set(uj.get(src, 0))
    comp = ds.get("compile") or {}
    counter("paddle_tpu_compile_fresh_total",
            "fresh XLA compiles (disk cache missed)").set(
        comp.get("fresh_compiles", 0))
    counter("paddle_tpu_compile_disk_cache_hits_total",
            "executables loaded from the persistent cache").set(
        comp.get("disk_cache_hits", 0))
    counter("paddle_tpu_compile_backend_seconds_total",
            "cumulative backend compile seconds").set(
        comp.get("backend_compile_s", 0.0))
    g_tts = gauge("paddle_tpu_time_to_first_step_seconds",
                  "process start to first compiled step", ("engine",))
    for kind, v in (comp.get("time_to_first_step_s") or {}).items():
        g_tts.labels(engine=kind).set(v)
    c_fault = counter("paddle_tpu_fault_events_total",
                      "resilience fault events", ("fault",))
    for kind, n in (ds.get("fault_events") or {}).items():
        c_fault.labels(fault=kind).set(n)
    poll_memory_gauges()
    return ds


def poll_memory_gauges(device=None):
    """Mirror device-memory stats (runtime/memory.py) into gauges —
    called at step boundaries by `TelemetryCallback` and by every
    `sync_runtime_metrics`. Degrades to zeros on backends without
    memory stats (CPU)."""
    try:
        from . import memory as _memory

        stats = _memory.memory_stats(device)
    except Exception:  # noqa: BLE001 — no jax / no backend: stay silent
        return None
    g = gauge("paddle_tpu_memory_bytes", "device memory (XLA arena)",
              ("stat",))
    for key, stat in (("bytes_in_use", "in_use"),
                      ("peak_bytes_in_use", "peak_in_use"),
                      ("bytes_limit", "limit")):
        if key in stats:
            g.labels(stat=stat).set(int(stats[key]))
    return stats


# ---------------------------------------------------------------------------
# schema (gated by tools/telemetry_smoke.py --check-schema)

# every metric family the stack registers, by name. Dashboards and the
# Prometheus textfile key on these — renaming one is a breaking change
# and must show up as a reviewed diff of tools/telemetry_schema.json.
METRIC_NAMES = (
    "paddle_tpu_dispatch_cache_hits_total",
    "paddle_tpu_dispatch_cache_misses_total",
    "paddle_tpu_dispatch_cache_evictions_total",
    "paddle_tpu_dispatch_cache_size",
    "paddle_tpu_dispatch_bypasses_total",
    "paddle_tpu_dispatch_unkeyable_total",
    "paddle_tpu_dispatch_fallbacks_total",
    "paddle_tpu_dispatch_warming_total",
    "paddle_tpu_dispatch_manifest_preloads_total",
    "paddle_tpu_fusion_flushes_total",
    "paddle_tpu_fusion_flush_reason_total",
    "paddle_tpu_fusion_recorded_ops_total",
    "paddle_tpu_fusion_flushed_ops_total",
    "paddle_tpu_op_hits_total",
    "paddle_tpu_op_misses_total",
    "paddle_tpu_op_retraces_total",
    "paddle_tpu_op_compile_seconds_total",
    "paddle_tpu_op_run_seconds",
    "paddle_tpu_unjittable_ops",
    "paddle_tpu_compile_fresh_total",
    "paddle_tpu_compile_disk_cache_hits_total",
    "paddle_tpu_compile_backend_seconds_total",
    "paddle_tpu_time_to_first_step_seconds",
    "paddle_tpu_fault_events_total",
    "paddle_tpu_memory_bytes",
    "paddle_tpu_train_steps_total",
    "paddle_tpu_step_seconds",
    "paddle_tpu_loss",
    "paddle_tpu_throughput_samples_per_sec",
    "paddle_tpu_grad_norm",
    "paddle_tpu_checkpoint_save_seconds",
    "paddle_tpu_checkpoint_restore_seconds",
    # input-pipeline visibility (ROADMAP item 4's prerequisite): per-
    # batch "step time waiting on data", recorded by Model.fit around
    # the loader's next() and reconciled against the data_wait spans
    "paddle_tpu_data_wait_seconds",
    "paddle_tpu_data_wait_seconds_last",
    # async input pipeline (io/prefetch.py): per-batch host→device
    # commit time — histogram fed from the SAME measurement as the
    # io/h2d span (tracing.reconcile_with_metrics holds the pair
    # exact) — plus the prefetcher's overlap/stall/depth view
    "paddle_tpu_h2d_seconds",
    "paddle_tpu_prefetch_depth",
    "paddle_tpu_prefetch_overlap_ratio",
    "paddle_tpu_prefetch_stalls_total",
    # serving engine (paddle_tpu/inference/engine.py + kv_cache.py):
    # per-request latency histograms (the "millions of users" p50/p99
    # metric), throughput counters, and paged-KV occupancy gauges —
    # request/ttft histograms are fed from the SAME measurement as
    # their serve/ spans (tracing.reconcile_with_metrics checks)
    "paddle_tpu_serve_request_seconds",
    "paddle_tpu_serve_ttft_seconds",
    "paddle_tpu_serve_requests_total",
    "paddle_tpu_serve_tokens_total",
    "paddle_tpu_serve_steps_total",
    "paddle_tpu_serve_tokens_per_sec",
    "paddle_tpu_serve_kv_blocks",
    # request-scoped observability (ISSUE 20): per-token decode latency
    # (TPOT) histogram, the rolling-window SLO surface published by
    # runtime/windows.ServingWindows as {window="1m"|"5m"}-labelled
    # gauges, and the server-published oldest-queued-age gauge that
    # replaced loadgen's client-side wedge inference
    "paddle_tpu_serve_tpot_seconds",
    "paddle_tpu_serve_ttft_p99_seconds",
    "paddle_tpu_serve_goodput_tokens_per_sec",
    "paddle_tpu_serve_shed_ratio",
    "paddle_tpu_serve_queue_depth_highwater",
    "paddle_tpu_serve_oldest_queued_age_seconds",
)

# every event `kind` the stack emits into the structured stream
EVENT_KINDS = (
    "train_begin",        # hapi.TelemetryCallback lifecycle
    "train_step",         # one per train batch (step time, loss, ...)
    "train_end",
    "fault",              # every record_fault() (runtime/resilience.py)
    "checkpoint_save",    # io/checkpoint.py, with duration + step
    "checkpoint_restore",
    "watchdog_start",     # distributed/elastic.py transitions
    "watchdog_stall",
    "watchdog_stop",
    "heartbeat_started",  # first tick() of an ElasticManager
    "compile",            # runtime/warmup.py: one backend compile (or
    #                       disk load) with its duration
    "compile_cache_hit",  # persistent-cache disk hit
    "precompile",         # warm-start AOT precompile summary
    "rendezvous",         # distributed/coordination.py barrier outcome
    #                       (leader published / follower ok / timeout)
    "cluster_merge",      # host-0 cross-rank telemetry + fault-log merge
    "checkpoint_discard",  # coordinated-restart truncation: steps newer
    #                        than the agreed restore step were deleted
    "trace_merge",        # host-0 span-trace merge into the cluster
    #                       timeline (runtime/tracing.py)
    "postmortem_dump",    # runtime/diagnostics.py wrote a bundle
    #                       (reason + path)
    "statusz_start",      # the /statusz introspection server bound
    #                       its port
    "serve_drain",        # inference/engine.py graceful drain began /
    #                       ended (queued+running counts, shed count)
    "serve_recover",      # a restarted engine re-admitted unfinished
    #                       journaled requests (resumed/completed
    #                       counts)
    "serve_access",       # one tail-sampled request left the engine
    #                       (inference/access_log.py): the access
    #                       record's summary fields for slow/shed/
    #                       evicted requests — happy-path requests
    #                       stay out of the stream by design
    "slo_burn",           # runtime/windows.SLOMonitor: both the fast
    #                       and slow windows burned error budget past
    #                       threshold (cooldown-limited)
)


def schema():
    """The frozen metric/event/fault vocabulary, as compared against
    tools/telemetry_schema.json by the CI freshness gate (and cross-
    checked against in-tree record_fault()/emit() literals by
    tools/staticcheck.py's schema-consistency pass)."""
    # lazy: resilience imports fine without jax, but telemetry must not
    # couple its import to another runtime module at module top
    from . import resilience as _resilience

    return {"version": SCHEMA_VERSION,
            "metrics": sorted(METRIC_NAMES),
            "events": sorted(EVENT_KINDS),
            "fault_kinds": sorted(_resilience._EVENT_KINDS)}


# ---------------------------------------------------------------------------
# process wiring: env-driven auto-config

if os.environ.get("PADDLE_TPU_TELEMETRY_DIR"):
    try:
        configure()
    except Exception:  # pragma: no cover — never break import
        pass
