"""Rolling-window metric aggregation + SLO burn-rate monitoring
(ISSUE 20).

The registry's counters and histograms (telemetry.py) are
lifetime-cumulative — right for "how much happened ever", useless for
"what is TTFT p99 *right now*". This module adds the windowed view as a
ring of epoch-tagged subwindows: each observation lands in the
subwindow slot for `int(now / width) % n`, a slot whose stored epoch is
stale is reset-then-written IN THE SAME critical section, and reads
merge every slot whose epoch still falls inside the window. One lock
per windowed metric makes the reset-vs-increment race at a rotation
boundary impossible by construction: an increment either lands in the
old epoch's slot before the reset (and ages out with it) or in the
fresh epoch after it — never in the void between
(tests/test_request_observability.py hammers this with concurrent
producers across hundreds of rotations).

Quantiles come from the same fixed-bucket histogram shape the registry
uses (mergeability was the reason buckets are fixed at declaration;
windowed interpolation is the payoff), so a windowed TTFT p99 and the
lifetime `paddle_tpu_serve_ttft_seconds` histogram describe the same
measurements on two time horizons.

`ServingWindows` bundles the serving engine's windowed surface (TTFT
p99, goodput tok/s, shed ratio, queue-depth highwater over 1m/5m) and
publishes it as `{window="1m"|"5m"}`-labelled registry gauges so
Prometheus//statusz scrape it like any other metric. `SLOMonitor`
implements the standard fast/slow multi-window burn-rate alert: when
BOTH the fast and the slow window burn error budget faster than their
thresholds, it emits one (cooldown-limited) ``slo_burn`` event into the
structured stream.

Everything here is pure host-side bookkeeping: no file I/O under any
lock, observation cost is one lock acquire + O(1) arithmetic, and every
method takes an optional ``now`` so tests drive time deterministically.
"""
from __future__ import annotations

import threading
import time

from . import telemetry as _telemetry

__all__ = ["WindowedCounter", "WindowedMax", "WindowedHistogram",
           "quantile_from_buckets", "ServingWindows", "SLOMonitor"]


def _now_or(now):
    return time.monotonic() if now is None else float(now)


class WindowedCounter:
    """A counter over the trailing `window_s` seconds, resolved into
    `subwindows` ring slots. `total()` is exact to one subwindow width
    of horizon fuzz (the standard rolling-window tradeoff)."""

    __slots__ = ("window_s", "n", "width", "_lock", "_slots")

    def __init__(self, window_s=60.0, subwindows=12):
        if window_s <= 0 or subwindows < 1:
            raise ValueError("window_s and subwindows must be positive")
        self.window_s = float(window_s)
        self.n = int(subwindows)
        self.width = self.window_s / self.n
        self._lock = threading.Lock()
        self._slots = [[-1, 0.0] for _ in range(self.n)]  # [epoch, value]

    def inc(self, n=1, now=None):
        epoch = int(_now_or(now) / self.width)
        slot = self._slots[epoch % self.n]
        with self._lock:
            # stale-slot reset and the increment share ONE critical
            # section: a producer racing the rotation boundary can
            # never have its increment wiped by a concurrent reset
            if slot[0] != epoch:
                slot[0] = epoch
                slot[1] = 0.0
            slot[1] += n

    def total(self, now=None):
        epoch = int(_now_or(now) / self.width)
        lo = epoch - self.n + 1
        with self._lock:
            return float(sum(v for e, v in self._slots if lo <= e <= epoch))

    def rate(self, now=None):
        """Per-second rate over the window."""
        return self.total(now) / self.window_s


class WindowedMax:
    """High-watermark over the trailing window (queue-depth peaks)."""

    __slots__ = ("window_s", "n", "width", "_lock", "_slots")

    def __init__(self, window_s=60.0, subwindows=12):
        if window_s <= 0 or subwindows < 1:
            raise ValueError("window_s and subwindows must be positive")
        self.window_s = float(window_s)
        self.n = int(subwindows)
        self.width = self.window_s / self.n
        self._lock = threading.Lock()
        self._slots = [[-1, None] for _ in range(self.n)]  # [epoch, max]

    def observe(self, v, now=None):
        v = float(v)
        epoch = int(_now_or(now) / self.width)
        slot = self._slots[epoch % self.n]
        with self._lock:
            if slot[0] != epoch:
                slot[0] = epoch
                slot[1] = v
            elif slot[1] is None or v > slot[1]:
                slot[1] = v

    def value(self, now=None):
        """Max over the window, or None when nothing was observed."""
        epoch = int(_now_or(now) / self.width)
        lo = epoch - self.n + 1
        with self._lock:
            vals = [v for e, v in self._slots
                    if lo <= e <= epoch and v is not None]
        return max(vals) if vals else None


def quantile_from_buckets(bounds, bucket_counts, count, q):
    """Interpolated quantile (q in [0, 100]) from fixed-bucket
    histogram counts (`bucket_counts` has len(bounds)+1 entries, the
    last being the +Inf tail). Returns None with no samples; the +Inf
    tail clamps to the last finite bound (the Prometheus
    `histogram_quantile` convention)."""
    if count <= 0:
        return None
    rank = max(1.0, q / 100.0 * count)
    cum = 0.0
    lower = 0.0
    for i, b in enumerate(bounds):
        c = bucket_counts[i]
        if c > 0 and cum + c >= rank:
            frac = (rank - cum) / c
            return lower + (b - lower) * min(1.0, max(0.0, frac))
        cum += c
        lower = b
    return float(bounds[-1])


class WindowedHistogram:
    """Fixed-bucket histogram over the trailing window: same bucket
    bounds as the lifetime registry histogram it shadows, so the two
    describe identical measurements on different horizons."""

    __slots__ = ("window_s", "n", "width", "bounds", "_lock", "_slots")

    def __init__(self, buckets, window_s=60.0, subwindows=12):
        if window_s <= 0 or subwindows < 1:
            raise ValueError("window_s and subwindows must be positive")
        self.window_s = float(window_s)
        self.n = int(subwindows)
        self.width = self.window_s / self.n
        self.bounds = tuple(sorted(buckets))
        self._lock = threading.Lock()
        # [epoch, bucket_counts, sum, count] per slot
        self._slots = [[-1, [0] * (len(self.bounds) + 1), 0.0, 0]
                       for _ in range(self.n)]

    def observe(self, v, now=None):
        v = float(v)
        bounds = self.bounds
        i = len(bounds)
        for j, b in enumerate(bounds):  # ~16 bounds: linear is fine
            if v <= b:
                i = j
                break
        epoch = int(_now_or(now) / self.width)
        slot = self._slots[epoch % self.n]
        with self._lock:
            if slot[0] != epoch:
                slot[0] = epoch
                slot[1] = [0] * (len(bounds) + 1)
                slot[2] = 0.0
                slot[3] = 0
            slot[1][i] += 1
            slot[2] += v
            slot[3] += 1

    def merged(self, now=None):
        """(bucket_counts, sum, count) merged over the live window."""
        epoch = int(_now_or(now) / self.width)
        lo = epoch - self.n + 1
        counts = [0] * (len(self.bounds) + 1)
        total = 0.0
        n = 0
        with self._lock:
            for e, bc, s, c in self._slots:
                if lo <= e <= epoch:
                    for i, v in enumerate(bc):
                        counts[i] += v
                    total += s
                    n += c
        return counts, total, n

    def quantile(self, q, now=None):
        counts, _total, n = self.merged(now)
        return quantile_from_buckets(self.bounds, counts, n, q)

    def count(self, now=None):
        return self.merged(now)[2]


# default serving windows: last minute at 5s resolution, last five
# minutes at 15s resolution — the fast/slow pair SLO burn rates expect
DEFAULT_WINDOWS = (("1m", 60.0, 12), ("5m", 300.0, 20))


class ServingWindows:
    """The serving engine's windowed SLO surface, published as
    `{window=...}`-labelled registry gauges (Prometheus//statusz pick
    them up like any lifetime metric). One instance per engine; the
    gauge families are shared process-wide (registry idempotence), so
    the last publisher wins — same contract as every engine-level
    gauge."""

    def __init__(self, windows=DEFAULT_WINDOWS, ttft_buckets=None):
        if ttft_buckets is None:
            ttft_buckets = _telemetry.DEFAULT_BUCKETS
        self.windows = tuple((str(w), float(s), int(n))
                             for w, s, n in windows)
        self._ttft = {}
        self._tokens = {}
        self._shed = {}
        self._submitted = {}
        self._qhw = {}
        for w, s, n in self.windows:
            self._ttft[w] = WindowedHistogram(ttft_buckets, s, n)
            self._tokens[w] = WindowedCounter(s, n)
            self._shed[w] = WindowedCounter(s, n)
            self._submitted[w] = WindowedCounter(s, n)
            self._qhw[w] = WindowedMax(s, n)
        self._g_ttft = _telemetry.gauge(
            "paddle_tpu_serve_ttft_p99_seconds",
            "TTFT p99 over the trailing window (0 = no samples)",
            ("window",))
        self._g_goodput = _telemetry.gauge(
            "paddle_tpu_serve_goodput_tokens_per_sec",
            "completed-request tokens per second over the trailing window",
            ("window",))
        self._g_shed = _telemetry.gauge(
            "paddle_tpu_serve_shed_ratio",
            "shed / submitted over the trailing window", ("window",))
        self._g_qhw = _telemetry.gauge(
            "paddle_tpu_serve_queue_depth_highwater",
            "max observed queue depth over the trailing window",
            ("window",))

    # -- producers (engine decode thread + submitters) ----------------------

    def observe_ttft(self, dt, now=None):
        for w, _, _ in self.windows:
            self._ttft[w].observe(dt, now)

    def count_submitted(self, now=None):
        for w, _, _ in self.windows:
            self._submitted[w].inc(1, now)

    def count_shed(self, now=None):
        for w, _, _ in self.windows:
            self._shed[w].inc(1, now)

    def count_tokens(self, n, now=None):
        for w, _, _ in self.windows:
            self._tokens[w].inc(n, now)

    def observe_queue_depth(self, depth, now=None):
        for w, _, _ in self.windows:
            self._qhw[w].observe(depth, now)

    # -- consumers (statusz / reports / gauges) -----------------------------

    def snapshot(self, now=None):
        """{window: panel} — quantiles, rates, ratios, highwater."""
        now = _now_or(now)
        out = {}
        for w, _s, _n in self.windows:
            counts, total, cnt = self._ttft[w].merged(now)
            sub = self._submitted[w].total(now)
            shed = self._shed[w].total(now)
            out[w] = {
                "ttft_p50_s": quantile_from_buckets(
                    self._ttft[w].bounds, counts, cnt, 50),
                "ttft_p99_s": quantile_from_buckets(
                    self._ttft[w].bounds, counts, cnt, 99),
                "ttft_count": cnt,
                "ttft_sum_s": total,
                "goodput_tokens_per_sec": self._tokens[w].rate(now),
                "submitted": sub,
                "shed": shed,
                "shed_ratio": (shed / sub) if sub else 0.0,
                "queue_depth_highwater": self._qhw[w].value(now),
            }
        return out

    def publish(self, now=None):
        """Refresh the windowed gauges; returns the snapshot. A None
        quantile publishes as 0.0 (gauges cannot carry None — the
        snapshot keeps the distinction)."""
        snap = self.snapshot(now)
        for w, panel in snap.items():
            self._g_ttft.labels(window=w).set(panel["ttft_p99_s"] or 0.0)
            self._g_goodput.labels(window=w).set(
                panel["goodput_tokens_per_sec"])
            self._g_shed.labels(window=w).set(panel["shed_ratio"])
            self._g_qhw.labels(window=w).set(
                panel["queue_depth_highwater"] or 0)
        return snap


class SLOMonitor:
    """Fast/slow multi-window burn-rate evaluation.

    `observe(good)` counts one request against the objective (e.g.
    "completed with TTFT under threshold"). `evaluate()` computes each
    window's bad-fraction / error-budget burn rate; when the FAST
    window burns >= `fast_burn` x budget AND the SLOW window burns >=
    `slow_burn` x budget (both with enough samples), it emits one
    ``slo_burn`` event — the cooldown keeps a sustained violation from
    flooding the stream. The two-window AND is the standard guard: the
    fast window gives detection latency, the slow window keeps a brief
    blip from paging anyone."""

    def __init__(self, name, objective=0.99,
                 fast=("1m", 60.0, 12), slow=("5m", 300.0, 20),
                 fast_burn=6.0, slow_burn=3.0, cooldown_s=30.0,
                 min_samples=10):
        if not 0.0 < objective < 1.0:
            raise ValueError("objective must be in (0, 1)")
        self.name = str(name)
        self.objective = float(objective)
        self.budget = 1.0 - self.objective
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.cooldown_s = float(cooldown_s)
        self.min_samples = int(min_samples)
        self._windows = {"fast": fast, "slow": slow}
        self._good = {k: WindowedCounter(s, n)
                      for k, (_w, s, n) in self._windows.items()}
        self._bad = {k: WindowedCounter(s, n)
                     for k, (_w, s, n) in self._windows.items()}
        self._last_burn = None
        self.burns_emitted = 0

    def observe(self, good, now=None):
        for k in self._windows:
            (self._good if good else self._bad)[k].inc(1, now)

    def evaluate(self, now=None):
        """Returns the panel dict (per-window bad ratio / burn rate /
        sample count, plus `burning`); emits ``slo_burn`` when both
        windows burn past threshold and the cooldown allows."""
        now = _now_or(now)
        panel = {"slo": self.name, "objective": self.objective,
                 "windows": {}}
        burns = {}
        for k, (label, _s, _n) in self._windows.items():
            good = self._good[k].total(now)
            bad = self._bad[k].total(now)
            total = good + bad
            ratio = (bad / total) if total else 0.0
            burns[k] = {"n": total, "burn": ratio / self.budget}
            panel["windows"][label] = {
                "samples": int(total), "bad_ratio": ratio,
                "burn_rate": burns[k]["burn"]}
        burning = (burns["fast"]["n"] >= self.min_samples
                   and burns["slow"]["n"] >= self.min_samples
                   and burns["fast"]["burn"] >= self.fast_burn
                   and burns["slow"]["burn"] >= self.slow_burn)
        panel["burning"] = burning
        if burning and (self._last_burn is None
                        or now - self._last_burn >= self.cooldown_s):
            self._last_burn = now
            self.burns_emitted += 1
            _telemetry.emit(
                "slo_burn", slo=self.name, objective=self.objective,
                fast_burn=round(burns["fast"]["burn"], 3),
                slow_burn=round(burns["slow"]["burn"], 3),
                fast_samples=int(burns["fast"]["n"]),
                slow_samples=int(burns["slow"]["n"]))
        return panel
