"""Crash-and-hang observability: flight recorder, postmortem bundles,
and the live /statusz introspection server.

The runtime can *count* (runtime/telemetry.py) and *time*
(runtime/tracing.py) nearly everything it does — but all of it lives
in the process, and when the process dies or wedges the evidence dies
with it: five bench rounds in a row produced zero TPU data (rc=124 /
backend-init crashes) and left nothing but a stderr tail, and a
watchdog stall reports a heartbeat age with no stacks and no runtime
state. Deferred/fused runtimes (LazyTensor) make this worse by design:
a failure surfaces at a flush site far from its cause, so the runtime
itself must carry its recent history to the grave. Three pieces:

* **Flight recorder** — an always-on, bounded, lock-cheap in-memory
  ring of the most recent spans/instants/events/faults, fed from the
  SAME emission points tracing and telemetry already own (a tap
  registered into ``tracing.set_flight_tap`` /
  ``telemetry.set_flight_tap``), active even when ``PADDLE_TPU_TRACE``
  is off. Kill switch = ``PADDLE_TPU_DIAGNOSTICS=0`` (or
  `set_enabled(False)`): disabled, hot paths pay exactly one falsy
  check — the same contract as tracing, locked by the parity test in
  tests/test_diagnostics.py. When a diagnostics directory is
  configured the ring additionally *spills* append-only to
  ``flight-<host>-<pid>.jsonl`` (bounded rotation, buffered flush
  every few records) so even a ``kill -9`` leaves a contiguous prefix
  of the run's recent history on disk.

* **Postmortem bundles** — `dump(reason)` writes ONE atomic,
  bounded-size JSON bundle: all-thread stacks, ``dispatch_stats()``
  (incl. fusion flush sites), the fault-event counters + recent fault
  log, a bounded telemetry registry snapshot, span phase totals, the
  flight-recorder tail, registered serving-engine state, and an
  env/config/version fingerprint. `install()` arms it on fatal
  signals (SIGTERM/SIGABRT, chaining to any previous handler),
  unhandled-exception exit (sys.excepthook), and hard crashes
  (``faulthandler`` into a sidecar file); the elastic watchdog dumps
  on stall and bench campaign children dump when their per-config
  deadline kills them — a deadline-killed config finally leaves
  evidence instead of ``rc=124``.

* **/statusz server** — an opt-in (``PADDLE_TPU_STATUSZ=<port>``),
  loopback-only-by-default stdlib HTTP server for live introspection:
  ``/statusz`` (the machine-readable `profiler.summary_dict()` runtime
  summary), ``/metrics`` (the existing Prometheus renderer),
  ``/stacks`` (all-thread stacks), ``/flightrecorder`` (the ring
  tail), ``/serving`` (engine + scheduler + KV-pool state),
  ``/requestz`` (per-request serving timelines: in-flight table,
  recent access records, windowed SLO panel). Port 0
  binds an ephemeral port; `statusz_address()` reports it and the
  bound port is also written to ``statusz-<pid>.port`` in the
  diagnostics dir so tooling can find a child's server.

Import-weight contract: stdlib only (runtime/__init__ imports this
eagerly so the recorder taps arm at import). jax / dispatch state is
only read through ``sys.modules`` guards — a dying or jax-less process
must still be able to write a bundle.
"""
from __future__ import annotations

import atexit
import collections
import faulthandler
import itertools
import json
import os
import signal
import socket
import sys
import threading
import time
import traceback
import warnings
import weakref

from . import collective_schedule as _csched
from . import telemetry as _telemetry
from . import tracing as _tracing

__all__ = [
    "enabled", "set_enabled", "configure", "diagnostics_dir",
    "recorder", "flight_tail", "flight_stats", "flight_spill_path",
    "read_flight_spill",
    "dump", "maybe_dump", "last_bundle_path", "read_bundle",
    "install", "installed", "ensure_installed",
    "start_statusz", "stop_statusz", "statusz_address",
    "register_serving_engine", "serving_snapshot",
    "thread_stacks", "runtime_fingerprint",
    "BUNDLE_PREFIX", "FLIGHT_PREFIX",
]

BUNDLE_PREFIX = "postmortem-"
FLIGHT_PREFIX = "flight-"


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return int(default)


def _env_flag(name, default):
    return os.environ.get(name, default).lower() not in ("0", "false", "no")


# the one falsy check hot paths pay when diagnostics is killed (same
# idiom as tracing._on / fusion._ON)
_on = [_env_flag("PADDLE_TPU_DIAGNOSTICS", "1")]

_lock = threading.Lock()              # config / install / server swaps
_config = {"dir": None}
_installed = {"signals": False, "excepthook": False, "faulthandler": False}
_prev_handlers = {}
_prev_excepthook = None
_last_bundle = [None]
_bundle_seq = itertools.count(1).__next__


# ---------------------------------------------------------------------------
# flight recorder

class FlightRecorder:
    """Bounded ring of recent diagnostic records.

    Recording costs a dict build + one uncontended lock around the
    seq-allocate/append pair (the "lock-cheap" contract — the lock is
    what makes ``seq`` order and append order the SAME order, which is
    the contiguity guarantee the bundles/spill assert; disabled, the
    tap's one falsy check is the whole cost). Every record carries a
    process-monotonic ``seq``: the tail is always a contiguous suffix
    of everything recorded, and the on-disk spill (when a diagnostics
    dir is configured) is a contiguous PREFIX-of-recent — a
    ``kill -9`` loses at most the spill buffer still in memory
    (``flush_every`` records)."""

    def __init__(self, capacity=None):
        self.capacity = max(16, capacity if capacity is not None else
                            _env_int("PADDLE_TPU_FLIGHT_CAPACITY", 4096))
        self._ring = collections.deque(maxlen=self.capacity)
        self._seq = itertools.count(1).__next__
        self._lock = threading.Lock()
        self.recorded = 0
        self._spill = None

    # -- recording (the hot path) ------------------------------------------
    def record(self, kind, **fields):
        rec = {"ts": round(time.time(), 6), "kind": kind}
        rec.update(fields)
        # seq allocation and append must be ONE atomic step: two
        # producers interleaving them would land out-of-seq records in
        # the ring/spill and break the asserted contiguity
        with self._lock:
            rec["seq"] = self._seq()
            self._ring.append(rec)
            self.recorded += 1
            sp = self._spill
            if sp is not None:
                sp.write(rec)  # threadlint: ok[CL003] buffered append (flushes 1-in-flush_every); ordering into the spill must match seq order, which requires writing under this lock

    # -- reading -----------------------------------------------------------
    def tail(self, n=None):
        """The most recent `n` records (all retained when n is None),
        oldest first. Snapshot-consistent enough for diagnostics: the
        ring may rotate under us, so copy first."""
        recs = list(self._ring)
        if n is not None:
            recs = recs[-int(n):]
        return recs

    def stats(self):
        held = len(self._ring)
        out = {"capacity": self.capacity, "recorded": self.recorded,
               "held": held,
               "overwritten": max(0, self.recorded - held)}
        sp = self._spill
        if sp is not None:
            # a spill whose rotation reopen failed is BROKEN — the
            # degradation must be visible wherever stats land
            # (/statusz, every bundle), never silent
            out["spill"] = {"path": sp.path, "ok": sp._f is not None}
        return out

    # -- spill (on-disk shadow, armed by configure()) ----------------------
    def set_spill(self, path, flush_every=None, max_bytes=None):
        new = None if path is None else _FlightSpill(
            path, flush_every=flush_every, max_bytes=max_bytes)
        with self._lock:  # record() reads _spill under this lock
            old, self._spill = self._spill, new
        if old is not None:
            old.close()
        return new

    def spill(self):
        return self._spill

    def flush_spill(self):
        sp = self._spill
        if sp is not None:
            sp.flush()


class _FlightSpill:
    """Append-only JSONL shadow of the ring: buffered (flushed every
    `flush_every` records — the kill -9 durability bound), rotated at
    `max_bytes` keeping one previous generation, and it NEVER raises
    into the recording path (full disk degrades to dropping)."""

    def __init__(self, path, flush_every=None, max_bytes=None):
        self.path = path
        self.flush_every = max(1, flush_every if flush_every is not None
                               else _env_int("PADDLE_TPU_FLIGHT_FLUSH_EVERY",
                                             16))
        self.max_bytes = max_bytes if max_bytes is not None else _env_int(
            "PADDLE_TPU_FLIGHT_MAX_BYTES", 4 * 1024 * 1024)
        self._lock = threading.Lock()
        self._pending = 0
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            self._f = open(path, "a")
        except OSError:
            self._f = None

    def write(self, rec):
        if self._f is None:
            return
        try:
            line = json.dumps(rec, default=str) + "\n"
        except (TypeError, ValueError):
            return
        with self._lock:
            if self._f is None:  # closed while we waited for the lock
                return
            try:
                self._f.write(line)  # threadlint: ok[CL003] bounded buffered append under the lock IS the durability contract (EventStream precedent)
                self._pending += 1
                if self._pending >= self.flush_every:
                    self._f.flush()  # threadlint: ok[CL003] see above
                    self._pending = 0
                    if self.max_bytes and self._f.tell() >= self.max_bytes:
                        self._rotate()
            except (OSError, ValueError):
                pass  # full disk / closed file: drop, never raise

    def _rotate(self):
        try:
            self._f.close()
        except (OSError, ValueError):
            pass
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass  # replace failed: reopen appends to the same file
        try:
            self._f = open(self.path, "a")  # threadlint: ok[CL003] rotation swaps the file atomically w.r.t. writers — the write caller holds the lock
        except OSError:
            # reopen failed (fd exhaustion, ENOSPC): mark the spill
            # BROKEN instead of leaving a closed file that swallows
            # every future write. No fault event from here — the
            # recorder lock is held and record_fault would re-enter it
            # through the telemetry tap; flight_stats() surfaces the
            # breakage in /statusz and every bundle instead.
            self._f = None

    def flush(self):
        if self._f is None:
            return
        with self._lock:
            try:
                self._f.flush()  # threadlint: ok[CL003] flush must serialize with writers — the durability contract (EventStream precedent)
                self._pending = 0
            except (OSError, ValueError):
                pass

    def close(self):
        if self._f is None:
            return
        with self._lock:
            try:
                self._f.flush()  # threadlint: ok[CL003] close is the last write; must serialize with in-flight records
                self._f.close()
            except (OSError, ValueError):
                pass
            self._f = None


_recorder = FlightRecorder()


def recorder():
    return _recorder


def flight_tail(n=None):
    """The flight recorder's most recent records, oldest first."""
    return _recorder.tail(n)


def flight_stats():
    return _recorder.stats()


def flight_spill_path():
    sp = _recorder.spill()
    return sp.path if sp is not None else None


def read_flight_spill(path, include_rotated=True):
    """Parse a flight spill file back (rotated generation first).
    Tolerates the kill -9 torn final line."""
    paths = ([path + ".1"] if include_rotated
             and os.path.exists(path + ".1") else []) + [path]
    out = []
    for p in paths:
        try:
            with open(p) as f:
                for line in f:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue  # torn tail line
        except OSError:
            continue
    return out


# -- the taps (registered into tracing/telemetry at import) -----------------

def _tap_span(kind, cat, name, wall_ts, dur_s, args):
    # `kind` in {"span", "instant"} — one falsy check when killed
    if not _on[0]:
        return
    if kind == "span":
        _recorder.record("span", cat=cat, name=name,
                         ts=round(wall_ts, 6), dur_s=round(dur_s, 6),
                         args=args)
    else:
        _recorder.record("instant", cat=cat, name=name, args=args)


def _tap_event(kind, fields):
    if not _on[0]:
        return
    # faults keep their own kind so a bundle/statusz reader can filter
    # degradations without string-matching inside fields
    if kind == "fault":
        _recorder.record("fault", fault=fields.get("fault"),
                         detail=fields.get("detail"),
                         count=fields.get("count"))
    else:
        _recorder.record("event", event=kind, fields=dict(fields))


def enabled():
    return _on[0]


def set_enabled(mode):
    """Runtime kill switch for the whole diagnostics layer: False
    disarms BOTH taps (killed, a hot path pays exactly the tap-slot
    falsy check; tracing's producer gate is re-derived so a process
    with tracing ALSO off goes back to one check per span site).
    Returns the previous state."""
    prev = _on[0]
    _on[0] = bool(mode)  # threadlint: ok[CL001] GIL-atomic flag publish; readers tolerate either value (set_warmup_count contract)
    # arm/disarm the taps symmetrically: span objects are not even
    # constructed when diagnostics was the only consumer, and a killed
    # layer costs telemetry.emit its one None check rather than a call
    _tracing.set_flight_tap(_tap_span if _on[0] else None)
    _telemetry.set_flight_tap(_tap_event if _on[0] else None)
    return prev


# arm the taps at import: the flight recorder is ALWAYS on (that is the
# point — the evidence must exist before anyone knew to ask for it)
_tracing.set_flight_tap(_tap_span if _on[0] else None)
_telemetry.set_flight_tap(_tap_event if _on[0] else None)


# ---------------------------------------------------------------------------
# configuration

def configure(directory=None):
    """Point diagnostics at `directory` (default:
    ``PADDLE_TPU_DIAGNOSTICS_DIR``): postmortem bundles land here and
    the flight recorder starts spilling its on-disk shadow. Returns
    the effective directory, or None when nowhere is configured."""
    directory = directory or os.environ.get("PADDLE_TPU_DIAGNOSTICS_DIR")
    if not directory:
        return None
    directory = os.path.abspath(directory)
    host = socket.gethostname()
    with _lock:
        if _config["dir"] == directory:
            return directory
        try:
            os.makedirs(directory, exist_ok=True)
        except OSError:
            # a failed reconfigure must leave any previously working
            # configuration (dir + spill) intact — silently losing the
            # bundle destination would disarm crash evidence while the
            # layer still LOOKS alive
            return None
        _config["dir"] = directory
        _recorder.set_spill(os.path.join(
            directory, f"{FLIGHT_PREFIX}{host}-{os.getpid()}.jsonl"))
    return directory


def diagnostics_dir():
    return _config["dir"]


# ---------------------------------------------------------------------------
# bundle capture

def thread_stacks():
    """All-thread stacks as {thread_label: [frame lines]} — the live
    equivalent of faulthandler's output, JSON-shaped."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        label = f"{names.get(ident, 'unknown')}-{ident}"
        out[label] = [ln.rstrip() for ln in
                      traceback.format_stack(frame)]
    return out


_ENV_PREFIXES = ("PADDLE_TPU_", "JAX_", "XLA_")


def runtime_fingerprint():
    """Env/config/version identity of this process: enough to tell two
    bundles apart (which jax, which knobs, which incarnation) without
    importing anything heavy — versions are read from ``sys.modules``
    so a jax-less or dying process still fingerprints."""
    fp = {"python": sys.version.split()[0],
          "platform": sys.platform,
          "host": socket.gethostname(),
          "pid": os.getpid(),
          "argv": sys.argv[:8],
          "env": {k: v for k, v in sorted(os.environ.items())
                  if k.startswith(_ENV_PREFIXES)}}
    for mod, key in (("jax", "jax"), ("jaxlib", "jaxlib"),
                     ("paddle_tpu", "paddle_tpu")):
        m = sys.modules.get(mod)
        v = getattr(m, "__version__", None) if m is not None else None
        fp[key] = v
    return fp


def _dispatch_snapshot():
    """dispatch_stats() (incl. fusion flush sites), read only when the
    dispatch layer is already imported — a bundle writer must never be
    the thing that first imports jax."""
    if "paddle_tpu.core.dispatch" not in sys.modules:
        return None
    try:
        return sys.modules["paddle_tpu.core.dispatch"].dispatch_stats()
    except Exception:  # noqa: BLE001 — evidence is best-effort
        return None


def _registry_snapshot(max_series=40):
    """Bounded telemetry registry snapshot: families keep at most
    `max_series` label series so one high-cardinality per-op family
    cannot blow the bundle size bound."""
    try:
        snap = _telemetry.snapshot()
    except Exception:  # noqa: BLE001
        return None
    out = {}
    for name, fam in snap.items():
        fam = dict(fam)
        series = fam.get("series") or []
        if len(series) > max_series:
            fam["series"] = series[:max_series]
            fam["series_dropped"] = len(series) - max_series
        out[name] = fam
    return out


def _span_snapshot():
    try:
        return {"phase_totals": _tracing.phase_totals(),
                "top_self_s": sorted(
                    ((f"{c}/{n}", round(v["self_s"], 6))
                     for (c, n), v in _tracing.span_stats().items()),
                    key=lambda kv: -kv[1])[:20]}
    except Exception:  # noqa: BLE001
        return None


def _fault_snapshot():
    try:
        from . import resilience as _res

        return {"counters": {k: v for k, v in _res.fault_events().items()
                             if v},
                "recent": [{"ts": round(ts, 6), "kind": k,
                            "detail": str(d)[:300] if d else None}
                           for ts, k, d in _res.fault_log(40)]}
    except Exception:  # noqa: BLE001
        return None


def _build_bundle(reason, extra, flight_n):
    bundle = {
        "bundle_version": 1,
        "reason": reason,
        "ts": round(time.time(), 6),
        "uptime_s": round(time.monotonic(), 3),
        "fingerprint": runtime_fingerprint(),
        "stacks": thread_stacks(),
        "dispatch": _dispatch_snapshot(),
        "faults": _fault_snapshot(),
        "telemetry": _registry_snapshot(),
        "spans": _span_snapshot(),
        "flight_recorder": {"stats": flight_stats(),
                            "tail": flight_tail(flight_n)},
        "serving": serving_snapshot(),
    }
    if extra:
        bundle["extra"] = extra
    return bundle


def dump(reason="manual", extra=None, directory=None):
    """Write one postmortem bundle; returns its path (None when no
    directory is configured, diagnostics is killed, or every write
    path failed — a dump may be the last thing a dying process does,
    so it NEVER raises). The bundle is bounded
    (``PADDLE_TPU_BUNDLE_MAX_BYTES``, default 1 MiB): oversize content
    sheds in evidence-value order (telemetry series first, then the
    flight tail, then stack depth) until it fits."""
    if not _on[0]:
        return None
    directory = directory or _config["dir"] or configure()
    if directory is None:
        return None
    try:
        max_bytes = max(16 * 1024,
                        _env_int("PADDLE_TPU_BUNDLE_MAX_BYTES", 1024 * 1024))
        bundle = _build_bundle(reason, extra,
                               _env_int("PADDLE_TPU_BUNDLE_FLIGHT_TAIL",
                                        400))
        blob = json.dumps(bundle, default=str)
        if len(blob) > max_bytes:
            bundle["telemetry"] = {"dropped": "bundle size bound"}
            blob = json.dumps(bundle, default=str)
        shrink = 200
        while len(blob) > max_bytes and shrink >= 1:
            bundle["flight_recorder"]["tail"] = \
                bundle["flight_recorder"]["tail"][-shrink:]
            bundle["flight_recorder"]["truncated"] = True
            blob = json.dumps(bundle, default=str)
            shrink //= 2
        if len(blob) > max_bytes:
            bundle["stacks"] = {k: v[-4:] for k, v in
                                bundle["stacks"].items()}
            blob = json.dumps(bundle, default=str)
        if len(blob) > max_bytes:
            # last resort: shed every heavy section but KEEP valid JSON
            # (a truncated byte cut would make the bundle unreadable —
            # worse than a thin one)
            for key in ("flight_recorder", "spans", "serving",
                        "dispatch", "faults"):
                bundle[key] = {"dropped": "bundle size bound"}
            blob = json.dumps(bundle, default=str)
        host = socket.gethostname()
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in str(reason))[:48] or "manual"
        path = os.path.join(
            directory,
            f"{BUNDLE_PREFIX}{host}-{os.getpid()}-"
            f"{_bundle_seq():04d}-{safe}.json")
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        with open(tmp, "w") as f:
            f.write(blob)
        os.replace(tmp, path)
        _last_bundle[0] = path  # threadlint: ok[CL001] GIL-atomic single-slot publish; readers tolerate either value
        _prune_bundles(directory)
        # the spill should cover everything up to the dump (the bundle
        # and the spill must agree about the final instants)
        _recorder.flush_spill()
        _telemetry.emit("postmortem_dump", reason=reason, path=path)
        return path
    except Exception as e:  # noqa: BLE001 — never raise out of a dump
        try:
            from .resilience import record_fault

            record_fault("postmortem_failures",
                         f"{reason}: {type(e).__name__}: {e}")
        except Exception:  # noqa: BLE001
            pass
        return None


def maybe_dump(reason, extra=None):
    """`dump`, but only when a diagnostics directory is already
    configured (env or explicit) — the form producers wire into
    failure paths so an unconfigured process pays nothing."""
    if not _on[0]:
        return None
    if _config["dir"] is None and \
            not os.environ.get("PADDLE_TPU_DIAGNOSTICS_DIR"):
        return None
    return dump(reason, extra=extra)


def _prune_bundles(directory, keep=None):
    keep = keep if keep is not None else _env_int(
        "PADDLE_TPU_BUNDLE_MAX_COUNT", 16)
    if keep <= 0:  # 0 = unbounded, like its sibling byte/rotation knobs
        return
    # oldest by mtime, not filename: bundle names start with pid + a
    # per-process counter, so a lexicographic order across processes
    # sharing a dir would prune by pid, not by age
    try:
        names = sorted(
            (n for n in os.listdir(directory)
             if n.startswith(BUNDLE_PREFIX) and n.endswith(".json")),
            key=lambda n: _mtime_or_zero(os.path.join(directory, n)))
    except OSError:
        return
    for n in names[:-keep]:
        try:
            os.remove(os.path.join(directory, n))
        except OSError:
            pass


def _mtime_or_zero(path):
    try:
        return os.path.getmtime(path)
    except OSError:
        return 0.0


def last_bundle_path():
    return _last_bundle[0]


def read_bundle(path):
    with open(path) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# fatal-path installation

def _on_fatal_signal(signum, frame):
    try:
        name = signal.Signals(signum).name
    except ValueError:  # pragma: no cover
        name = str(signum)
    # the handler runs on the main thread BETWEEN bytecodes — the
    # interrupted frame may be holding a telemetry/spill lock (they are
    # non-reentrant), so dumping inline could deadlock the dying
    # process. Dump from a helper thread and give it a bounded join:
    # if the main thread holds a lock the dump needs, the join expires
    # and the process still dies with the expected exit status (a
    # missing bundle beats a hang that turns the SIGTERM grace period
    # into a SIGKILL with no evidence at all).
    th = threading.Thread(target=dump, args=(f"signal_{name}",),  # threadlint: ok[CL006] bundle writes are atomic (pid+tid tmp -> os.replace) and the bounded join below IS the shutdown ordering; a teardown-torn tmp never shadows a bundle
                          daemon=True)
    th.start()
    th.join(timeout=10.0)
    try:
        _tracing.flush()
    except Exception:  # noqa: BLE001
        pass
    prev = _prev_handlers.get(signum)
    if callable(prev):
        prev(signum, frame)
        return
    if prev == signal.SIG_IGN:
        return
    # default disposition: restore it and re-raise so the exit status
    # (e.g. rc = -SIGTERM) is exactly what the parent expects
    signal.signal(signum, signal.SIG_DFL)
    os.kill(os.getpid(), signum)


def _on_unhandled(exc_type, exc, tb):
    dump("unhandled_exception",
         extra={"exception": "".join(
             traceback.format_exception(exc_type, exc, tb))[-4000:]})
    hook = _prev_excepthook or sys.__excepthook__
    hook(exc_type, exc, tb)


def install(catch_signals=(signal.SIGTERM, signal.SIGABRT)):
    """Arm the fatal paths: signal handlers (chained — a previous
    handler still runs, a default disposition is re-raised so exit
    codes survive), sys.excepthook, and faulthandler into a sidecar
    text file in the diagnostics dir (hard crashes — SIGSEGV et al. —
    cannot run Python, so their all-thread stacks go there). Signal
    handlers can only be installed from the main thread; elsewhere
    they are skipped (excepthook/faulthandler still arm). Idempotent;
    no-op while the kill switch is off or nowhere is configured."""
    global _prev_excepthook
    if not _on[0]:
        return False
    directory = _config["dir"] or configure()
    if directory is None:
        return False
    # hostname resolved BEFORE the lock (can block on a slow resolver —
    # the tracing.configure precedent)
    host = socket.gethostname()
    with _lock:
        if not _installed["faulthandler"]:
            try:
                fh = open(os.path.join(  # threadlint: ok[CL003,CL005] config-time once-per-process; the file is pid-keyed and owned by faulthandler (truncation IS the fresh-file contract)
                    directory,
                    f"faulthandler-{host}-{os.getpid()}.txt"), "w")
                faulthandler.enable(file=fh, all_threads=True)
                _installed["faulthandler"] = True
            except (OSError, ValueError):
                pass
        if not _installed["excepthook"]:
            _prev_excepthook = sys.excepthook
            sys.excepthook = _on_unhandled
            _installed["excepthook"] = True
        if threading.current_thread() is threading.main_thread():
            # per-signal idempotence: a signal already chained must
            # NEVER be re-installed — signal.signal would return OUR
            # handler as "previous" and the chain would recurse into
            # itself on delivery
            for sig in catch_signals:
                if sig in _prev_handlers:
                    continue
                try:
                    _prev_handlers[sig] = signal.signal(
                        sig, _on_fatal_signal)
                except (OSError, ValueError, RuntimeError):
                    pass
            _installed["signals"] = bool(_prev_handlers)
    return True


def installed():
    return dict(_installed)


def ensure_installed(default_dir=None):
    """The producer-side wiring hook (ResilienceCallback,
    ServingEngine, bench children): configure from the env — or
    `default_dir` when nothing else is configured — and arm the fatal
    paths + statusz if requested. Never raises."""
    try:
        d = _config["dir"] or configure()
        if d is None and default_dir is not None:
            d = configure(default_dir)
        if d is not None:
            install()
        if os.environ.get("PADDLE_TPU_STATUSZ") is not None:
            start_statusz()
        return d
    except Exception:  # noqa: BLE001 — observability must never raise
        return None


# ---------------------------------------------------------------------------
# serving registration (/serving route + bundle section)

_engines = []
_engines_lock = threading.Lock()


def register_serving_engine(engine):
    """Track a ServingEngine (weakly) so /serving and bundles can report
    engine + scheduler + KV-pool state."""
    with _engines_lock:
        _engines.append(weakref.ref(engine))
        if len(_engines) > 16:  # bound: drop dead refs, then oldest
            _engines[:] = [r for r in _engines if r() is not None][-16:]


def serving_snapshot():
    """State of every live registered engine (None when none)."""
    out = []
    for ref in list(_engines):
        eng = ref()
        if eng is None:
            continue
        try:
            out.append(eng.diagnostics_snapshot())
        except Exception as e:  # noqa: BLE001 — a wedged engine must
            # not take the route down with it
            out.append({"error": f"{type(e).__name__}: {e}"})
    return out or None


def requestz_snapshot():
    """Per-engine /requestz payloads (ISSUE 20): in-flight request
    table, recent access records, windowed SLO panel. None when no
    live engine is registered (or none exposes the snapshot)."""
    out = []
    for ref in list(_engines):
        eng = ref()
        if eng is None:
            continue
        snap_fn = getattr(eng, "requestz_snapshot", None)
        if snap_fn is None:
            continue
        try:
            out.append(snap_fn())
        except Exception as e:  # noqa: BLE001 — a wedged engine must
            # not take the route down with it
            out.append({"error": f"{type(e).__name__}: {e}"})
    return out or None


def _serving_slo():
    """Compact windowed-SLO panels for the /statusz body (the full
    request table lives on /requestz)."""
    out = []
    for ref in list(_engines):
        eng = ref()
        if eng is None:
            continue
        panel_fn = getattr(eng, "slo_panel", None)
        if panel_fn is None:
            continue
        try:
            out.append(panel_fn())
        except Exception as e:  # noqa: BLE001
            out.append({"error": f"{type(e).__name__}: {e}"})
    return out or None


# ---------------------------------------------------------------------------
# /statusz server

_server = [None]          # (httpd, thread, host, port)


def _statusz_payload():
    """The /statusz body: the machine-readable profiler summary when
    the profiler (and therefore jax) is already imported, else the
    light sections this module can produce alone."""
    try:
        # the profiler package imports jax at module top — only serve
        # the full summary when the dispatch layer (and therefore jax)
        # is already loaded, so a scrape is never the first jax import
        if "paddle_tpu.profiler" in sys.modules or \
                "paddle_tpu.core.dispatch" in sys.modules:
            from .. import profiler as _profiler

            summary = _profiler.summary_dict()
        else:
            summary = None
    except Exception:  # noqa: BLE001
        summary = None
    return {
        "ts": round(time.time(), 6),
        "fingerprint": runtime_fingerprint(),
        "summary": summary,
        "faults": _fault_snapshot(),
        "collectives": _csched.schedule_stats(),
        "flight_recorder": flight_stats(),
        "diagnostics_dir": _config["dir"],
        "last_bundle": _last_bundle[0],
        "threads": sorted(t.name for t in threading.enumerate()),
        "serving_slo": _serving_slo(),
    }


def _metrics_text():
    # sync only when the dispatch layer is already loaded — a scrape
    # must never be the thing that first imports jax into a process
    if "paddle_tpu.core.dispatch" in sys.modules:
        try:
            _telemetry.sync_runtime_metrics()
        except Exception:  # noqa: BLE001 — no dispatch traffic yet
            pass
    return _telemetry.render_prometheus()


def _make_handler():
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        server_version = "paddle_tpu_statusz/1"

        def _send(self, body, ctype="application/json"):
            data = body.encode() if isinstance(body, str) else body
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _json(self, obj):
            self._send(json.dumps(obj, default=str, indent=1))

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            path, _, query = self.path.partition("?")
            try:
                if path in ("/", "/statusz"):
                    self._json(_statusz_payload())
                elif path == "/metrics":
                    self._send(_metrics_text(),
                               "text/plain; version=0.0.4")
                elif path == "/stacks":
                    self._json({"ts": round(time.time(), 6),
                                "stacks": thread_stacks()})
                elif path == "/flightrecorder":
                    n = 200
                    for part in query.split("&"):
                        if part.startswith("n="):
                            try:
                                n = max(1, int(part[2:]))
                            except ValueError:
                                pass
                    self._json({"stats": flight_stats(),
                                "tail": flight_tail(n)})
                elif path == "/serving":
                    self._json({"engines": serving_snapshot() or []})
                elif path == "/requestz":
                    self._json({"engines": requestz_snapshot() or []})
                elif path == "/healthz":
                    self._send("ok\n", "text/plain")
                else:
                    self.send_error(404, "unknown route")
            except BrokenPipeError:  # client went away mid-write
                pass
            except Exception as e:  # noqa: BLE001 — a route bug must
                # not kill the server thread
                try:
                    self.send_error(500, f"{type(e).__name__}: {e}")
                except Exception:  # noqa: BLE001
                    pass
                try:
                    from .resilience import record_fault

                    record_fault("statusz_errors",
                                 f"{path}: {type(e).__name__}: {e}")
                except Exception:  # noqa: BLE001
                    pass

        def log_message(self, fmt, *args):  # quiet by default
            pass

    return Handler


def start_statusz(port=None, host=None):
    """Start the introspection server (idempotent; returns (host,
    port), or None when no port is configured or the bind failed).
    Loopback-only by default — /stacks and env fingerprints are not
    for the open network; ``PADDLE_TPU_STATUSZ_HOST`` (or `host=`)
    overrides for operators who front it themselves. Port 0 binds
    ephemeral; the effective port lands in `statusz_address()`, the
    ``statusz_start`` telemetry event, and ``statusz-<pid>.port`` in
    the diagnostics dir (when configured) so external tooling can
    find a child's server."""
    if not _on[0]:
        return None
    if port is None:
        raw = os.environ.get("PADDLE_TPU_STATUSZ")
        if raw is None or raw == "" or raw.lower() in ("false", "no"):
            return None
        try:
            port = int(raw)
        except ValueError:
            return None
    host = host or os.environ.get("PADDLE_TPU_STATUSZ_HOST", "127.0.0.1")
    with _lock:
        if _server[0] is not None:
            return _server[0][2], _server[0][3]
        try:
            from http.server import ThreadingHTTPServer

            httpd = ThreadingHTTPServer((host, int(port)), _make_handler())
        except OSError as e:
            try:
                from .resilience import record_fault

                record_fault("statusz_errors",
                             f"bind {host}:{port}: {e}")
            except Exception:  # noqa: BLE001
                pass
            return None
        httpd.daemon_threads = True
        bound = httpd.server_address[1]
        th = threading.Thread(target=httpd.serve_forever,
                              name="paddle_tpu-statusz", daemon=True)
        th.start()
        _server[0] = (httpd, th, host, bound)
    _telemetry.emit("statusz_start", host=host, port=bound)
    d = _config["dir"]
    if d is not None:
        # atomic publish: a poller must never read a torn/empty file
        p = os.path.join(d, f"statusz-{os.getpid()}.port")
        tmp = f"{p}.{threading.get_ident()}.tmp"
        try:
            with open(tmp, "w") as f:
                f.write(f"{host}:{bound}\n")
            os.replace(tmp, p)
        except OSError:
            pass
    return host, bound


def stop_statusz():
    with _lock:
        ent, _server[0] = _server[0], None
    if ent is None:
        return
    httpd = ent[0]
    try:
        httpd.shutdown()
        httpd.server_close()
    except Exception:  # noqa: BLE001
        pass


def statusz_address():
    ent = _server[0]
    return (ent[2], ent[3]) if ent is not None else None


# a clean exit leaves the spill complete (a kill -9 still loses at most
# the buffered tail — the durability bound the spill documents)
atexit.register(lambda: _recorder.flush_spill())


# ---------------------------------------------------------------------------
# process wiring: env-driven auto-config (same zero-user-code promise
# as tracing — a child with the env vars set needs no code changes)

if os.environ.get("PADDLE_TPU_DIAGNOSTICS_DIR"):
    try:
        configure()
        install()
    except Exception:  # pragma: no cover — never break import
        pass
if os.environ.get("PADDLE_TPU_STATUSZ") is not None:
    try:
        start_statusz()
    except Exception:  # pragma: no cover — never break import
        pass
