"""Host staging pool (Python side of csrc/staging_pool.cpp).

Reference capability: fluid/operators/reader/buffered_reader.cc — pinned
staging buffers between the data pipeline and the device. Workers memcpy
collated numpy batches into fixed 64-byte-aligned C++ slots (the ctypes call
releases the GIL, so copies parallelize across workers); the consumer wraps
each slot zero-copy with np.frombuffer and hands it to jax.device_put.
"""
from __future__ import annotations

import ctypes
import os
import threading

import numpy as np

__all__ = ["StagingPool", "staging_lib"]

_lib = None
_lib_lock = threading.Lock()


def staging_lib():
    """Build (cached) and load csrc/staging_pool.cpp via cpp_extension."""
    global _lib
    with _lib_lock:
        if _lib is None:
            from ..utils.cpp_extension import load

            src = os.path.join(os.path.dirname(__file__), "..", "..",
                               "csrc", "staging_pool.cpp")
            lib = load("staging_pool", [os.path.normpath(src)])
            lib.sp_create.restype = ctypes.c_void_p
            lib.sp_create.argtypes = [ctypes.c_int, ctypes.c_size_t]
            lib.sp_destroy.argtypes = [ctypes.c_void_p]
            lib.sp_slot_bytes.restype = ctypes.c_size_t
            lib.sp_slot_bytes.argtypes = [ctypes.c_void_p]
            lib.sp_num_slots.restype = ctypes.c_int
            lib.sp_num_slots.argtypes = [ctypes.c_void_p]
            lib.sp_acquire_write.restype = ctypes.c_int
            lib.sp_acquire_write.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.sp_slot_ptr.restype = ctypes.c_void_p
            lib.sp_slot_ptr.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.sp_copy_in.restype = ctypes.c_int
            lib.sp_copy_in.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_size_t, ctypes.c_void_p,
                                       ctypes.c_size_t]
            lib.sp_commit.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.sp_acquire_read.restype = ctypes.c_int
            lib.sp_acquire_read.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.sp_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
            _lib = lib
    return _lib


def _align(n, a=64):
    return (n + a - 1) // a * a


class StagingPool:
    """Fixed ring of aligned host slots; free/ready FIFO lives in C++."""

    def __init__(self, n_slots, slot_bytes):
        self._lib = staging_lib()
        self._pool = self._lib.sp_create(int(n_slots), int(slot_bytes))
        if not self._pool:
            raise MemoryError(
                f"staging pool alloc failed ({n_slots} x {slot_bytes} B)")
        self.n_slots = int(n_slots)
        self.slot_bytes = int(slot_bytes)

    # -- producer side ------------------------------------------------------
    def acquire_write(self, timeout_ms=-1):
        return self._lib.sp_acquire_write(self._pool, int(timeout_ms))

    def write_arrays(self, slot, arrays):
        """memcpy each ndarray into the slot (GIL-free); returns the offset
        metadata [(offset, shape, dtype), ...] needed to view them back."""
        meta = []
        offset = 0
        for a in arrays:
            a = np.ascontiguousarray(a)
            if offset + a.nbytes > self.slot_bytes:
                raise ValueError(
                    f"batch ({offset + a.nbytes} B) exceeds slot "
                    f"({self.slot_bytes} B)")
            rc = self._lib.sp_copy_in(self._pool, slot, offset,
                                      a.ctypes.data, a.nbytes)
            if rc != 0:
                raise RuntimeError("sp_copy_in failed")
            meta.append((offset, a.shape, a.dtype))
            offset = _align(offset + a.nbytes)
        return meta

    def stage(self, arrays, timeout_ms=-1):
        """acquire_write + write + commit; returns (slot, meta) or None."""
        slot = self.acquire_write(timeout_ms)
        if slot < 0:
            return None
        try:
            meta = self.write_arrays(slot, arrays)
        except Exception:
            self.release(slot)  # don't let a failed write shrink the ring
            raise
        self._lib.sp_commit(self._pool, slot)
        return slot, meta

    # -- consumer side ------------------------------------------------------
    def acquire_read(self, timeout_ms=-1):
        return self._lib.sp_acquire_read(self._pool, int(timeout_ms))

    def view_arrays(self, slot, meta):
        """Zero-copy np views of the staged arrays (valid until release)."""
        base = self._lib.sp_slot_ptr(self._pool, slot)
        views = []
        for offset, shape, dtype in meta:
            nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
            buf = (ctypes.c_char * nbytes).from_address(base + offset)
            views.append(np.frombuffer(buf, dtype=dtype).reshape(shape))
        return views

    def release(self, slot):
        self._lib.sp_release(self._pool, slot)

    def close(self):
        if self._pool:
            self._lib.sp_destroy(self._pool)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # interpreter teardown
            pass
