"""Structured span tracing: a per-step timeline from dispatch to
cluster, Perfetto-loadable, reconciled with telemetry.

The metrics registry (PR 5) and the flush-site attribution (PR 11) can
count nearly everything the runtime does, but counters have no time
axis: nothing answers "where did step k's wall time go — data wait,
trace recording, fused compile, flush execution, checkpoint, or a
stalled peer?" LazyTensor-style deferred-execution systems live or die
by understanding their trace/flush boundaries *in time*, and the
TVM-style autotuning loop ROADMAP item 5 plans presupposes exactly
this per-phase measurement. This module is that instrument: a
process-wide span tracer emitting Chrome Trace Event Format JSON that
loads directly in Perfetto / ``chrome://tracing``.

Design, mirroring the telemetry layer's contracts:

* **Opt-in + kill switch.** ``PADDLE_TPU_TRACE=<dir>`` (or
  `configure(dir)`) turns tracing on; every producer across the stack
  guards with one falsy check (``_on[0]``), so a disabled tracer costs
  hot paths exactly one list-index truthiness test and dispatch stats
  stay byte-identical to an untraced run (the kill-switch parity test
  in tests/test_tracing.py locks this).
* **Append-only, bounded buffers.** Spans buffer in memory (bounded by
  ``PADDLE_TPU_TRACE_FLUSH_EVERY``, default 64) and flush as complete
  JSON lines appended to the trace file — a ``kill -9`` loses at most
  the unflushed tail, never the run's history (the PR-5 event-stream
  durability contract). A per-process event cap
  (``PADDLE_TPU_TRACE_MAX_EVENTS``) bounds the file; overflow drops
  spans and counts them rather than growing without limit.
* **Chrome Trace Event Format.** The file is a JSON array of complete
  ("ph":"X") events — ``[`` then one object per line with a trailing
  comma, terminated with ``]`` on clean close. Chrome's own tracers
  emit exactly this shape and Perfetto accepts the unterminated form,
  so a killed process's trace still loads. ``ts`` is wall-clock epoch
  microseconds (cross-rank alignment in a merged timeline); durations
  come from ``perf_counter`` so they survive NTP steps.
* **Rank/pid/incarnation tags.** Every event's ``pid`` is the cluster
  rank when one is set (telemetry.set_rank / PADDLE_TPU_CLUSTER_RANK),
  else the OS pid; the per-process metadata record carries host, OS
  pid (the incarnation — a relaunched rank is a new pid) and
  ``PADDLE_TPU_CLUSTER_RUN_ID`` when exported. Per-process files are
  named ``trace-<host>-<pid>.json`` so ranks sharing one directory
  (the cluster default) never collide, and `telemetry.merge_cluster`
  tails them by byte offset into ONE cluster timeline.
* **Reconciliation.** Producers that already time an operation for the
  metrics registry (checkpoint save/restore, sampled op runs, the
  per-step histogram, data wait) emit their span from the SAME
  measured duration, so `reconcile_with_metrics()` can assert the
  per-phase span sums agree with ``dispatch_stats()`` / the telemetry
  histograms — the timeline and the counters can never tell different
  stories. tools/trace_smoke.py gates this in CI.

Import-weight contract: stdlib only (core/dispatch.py imports this
eagerly). Everything here is host-side control plane — wall-clock
reads exactly like the telemetry layer, never run under a trace.
"""
from __future__ import annotations

import atexit
import itertools
import json
import os
import socket
import threading
import time

from . import telemetry as _telemetry

__all__ = [
    "configure", "enabled", "set_enabled", "trace_dir", "trace_path",
    "tracer", "span", "emit_span", "instant", "set_span_arg",
    "set_flight_tap", "flush", "close",
    "span_stats", "phase_totals", "reset_span_stats", "summary_lines",
    "reconcile_with_metrics", "read_trace", "validate_trace",
    "TRACE_BASENAME_PREFIX",
]

TRACE_BASENAME_PREFIX = "trace-"


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return int(default)


# the producer-side switch: ONE list-index truthiness check on every
# hot path (the same idiom as fusion._ON). True while ANY consumer is
# live: a configured tracer with the kill switch on, OR the flight
# recorder's tap (runtime/diagnostics.py — always-on by default, so
# spans keep feeding the crash ring even when PADDLE_TPU_TRACE is off).
_on = [False]
# file tracing specifically (tracer configured AND its kill switch on):
# gates writes to the trace file and the span-stats aggregate, so the
# reconciliation/summary surfaces still cover exactly what the trace
# file covers
_live = [False]
# the flight-recorder tap: fn(kind, cat, name, wall_ts, dur_s, args)
# with kind in {"span", "instant"}, or None when diagnostics is off
_fr = [None]

_lock = threading.Lock()          # guards _tracer/_config swaps
_tracer = None
_config = {"dir": None}
_killed = [False]                 # set_enabled(False) latch


def _recompute_on():
    _on[0] = _live[0] or _fr[0] is not None


def set_flight_tap(fn):
    """Register (or, with None, disarm) the flight-recorder tap. Every
    span/instant emission point feeds it regardless of whether file
    tracing is on — diagnostics owns the ring, tracing owns the
    emission points. Returns the previous tap."""
    prev = _fr[0]
    _fr[0] = fn  # threadlint: ok[CL001] GIL-atomic publish; config-time single-writer (set_warmup_count contract)
    _recompute_on()
    return prev


class _TLocal(threading.local):
    stack = None  # list of live _Span frames (nesting/self-time)
    tids = None   # {tracer token: small Chrome tid}, assigned lazily


_tl = _TLocal()

_next_tracer_token = itertools.count(1).__next__


class SpanTracer:
    """One process's trace file: buffered, append-only, thread-safe.

    The buffer bound IS the durability bound: everything older than
    ``flush_every`` spans is on disk, so a SIGKILL loses at most the
    tail still in memory (tests/test_tracing.py proves it with a
    killed child)."""

    def __init__(self, path, flush_every=None, max_events=None):
        self.path = path
        self.flush_every = max(1, flush_every if flush_every is not None
                               else _env_int("PADDLE_TPU_TRACE_FLUSH_EVERY",
                                             64))
        self.max_events = max(1, max_events if max_events is not None
                              else _env_int("PADDLE_TPU_TRACE_MAX_EVENTS",
                                            1_000_000))
        self._lock = threading.Lock()
        self._buf = []
        self._meta_pid = None  # pid lane the last metadata record named
        self._closed = False
        self._host = socket.gethostname()
        self._os_pid = os.getpid()
        self._next_tid = 1
        # never-recycled tracer token: the per-thread tid cache keys on
        # it, so a reconfigured tracer re-assigns tids (and re-emits
        # thread_name metadata) instead of inheriting stale ones
        self._token = _next_tracer_token()
        self.emitted = 0
        self.dropped = 0
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        # "a": re-opening an existing path appends (a reconfigure to the
        # same dir in one process must not truncate history); the "["
        # array opener is written only for a fresh file. A previous
        # CLEAN close terminated the array with "{}]" — strip it first,
        # or every append would land past the "]" and the file would
        # fail both validate_trace and a strict-JSON load forever.
        fresh = not os.path.exists(path) or os.path.getsize(path) == 0
        if not fresh:
            self._strip_terminator(path)
        self._f = open(path, "a")
        if fresh:
            self._f.write("[\n")
            self._f.flush()

    @staticmethod
    def _strip_terminator(path):
        """Remove the exact ``{}]`` close-terminator (plus trailing
        whitespace) from an existing trace file so appends keep it
        parseable; foreign/unterminated files are left untouched."""
        try:
            with open(path, "rb+") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                back = min(size, 16)
                f.seek(size - back)
                tail = f.read(back)
                stripped = tail.rstrip()
                if stripped.endswith(b"{}]"):
                    f.truncate(size - back + len(stripped) - 3)
        except OSError:
            pass

    # -- identity ----------------------------------------------------------
    def _pid(self):
        # the Chrome "pid" lane: cluster rank when one is set (so a
        # merged timeline shows one process track per rank), else the
        # OS pid. Read per emit — the rank is set AFTER import in
        # cluster bring-up (coordination.init_cluster_telemetry).
        r = _telemetry.get_rank()
        return self._os_pid if r is None else int(r)

    def _tid(self):
        m = _tl.tids
        if m is None:
            m = _tl.tids = {}
        t = m.get(self._token)
        if t is None:
            with self._lock:
                t = self._next_tid
                self._next_tid += 1
            m[self._token] = t
            th = threading.current_thread()
            # pid lane stamped at flush time, like every buffered record
            self._push({"ph": "M", "name": "thread_name",
                        "tid": t, "ts": 0,
                        "args": {"name": th.name}})
        return t

    def _metadata(self, pid):
        """The per-process metadata record (rank/pid/incarnation tags)
        for one pid lane — emitted at flush time, and re-emitted when
        the lane changes (rank assigned at cluster bring-up), so both
        the pre-rank and rank lanes are named in Perfetto."""
        r = _telemetry.get_rank()
        name = (f"rank{r} " if r is not None else "") + \
            f"{self._host}:{self._os_pid}"
        args = {"name": name, "host": self._host, "os_pid": self._os_pid,
                "incarnation": self._os_pid}
        if r is not None:
            args["rank"] = int(r)
        run_id = os.environ.get("PADDLE_TPU_CLUSTER_RUN_ID")
        if run_id:
            args["run_id"] = run_id
        return {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "ts": 0, "args": args}

    # -- emission ----------------------------------------------------------
    def _push(self, rec):
        # caller holds no lock; buffer append + bounded flush under ours
        with self._lock:
            if self._closed:
                return
            if self.emitted + len(self._buf) >= self.max_events:
                self.dropped += 1  # bounded file: drop, count, never grow
                return
            self._buf.append(rec)
            if len(self._buf) >= self.flush_every:
                self._flush_locked()

    def emit_complete(self, name, cat, wall_start, dur_s, args=None,
                      tid=None):
        """One complete ("X") span: `wall_start` epoch seconds,
        `dur_s` a perf_counter-derived duration. The pid LANE is
        stamped at flush time, not here — a span emitted before the
        cluster rank was assigned but flushed after still lands on the
        rank lane of a merged timeline."""
        rec = {"name": name, "cat": cat, "ph": "X",
               "ts": int(wall_start * 1e6),
               "dur": max(0, int(dur_s * 1e6)),
               "tid": self._tid() if tid is None else tid}
        if args:
            rec["args"] = args
        self._push(rec)

    def emit_instant(self, name, cat, args=None):
        rec = {"name": name, "cat": cat, "ph": "i", "s": "p",
               "ts": int(time.time() * 1e6),
               "tid": self._tid()}
        if args:
            rec["args"] = args
        self._push(rec)

    def _flush_locked(self):
        if not self._buf:
            return
        pid = self._pid()
        if pid != self._meta_pid:
            # name this lane (first flush, or the rank was assigned
            # since — the old lane keeps its metadata, both stay named)
            self._meta_pid = pid
            self._buf.insert(0, self._metadata(pid))
        lines = []
        for rec in self._buf:
            rec.setdefault("pid", pid)
            try:
                lines.append(json.dumps(rec, default=str) + ",\n")
            except (TypeError, ValueError):
                continue
        self._buf = []
        try:
            self._f.write("".join(lines))  # threadlint: ok[CL003] append-only bounded-buffer flush under the lock IS the durability contract (same discipline as telemetry.EventStream)
            self._f.flush()  # threadlint: ok[CL003] see above — everything older than flush_every spans must be on disk
            self.emitted += len(lines)
        except (OSError, ValueError):
            pass  # closed file / full disk: drop, never raise into a step

    def flush(self):
        with self._lock:
            self._flush_locked()

    def close(self, terminate=True):
        """Flush and (by default) terminate the JSON array — the file
        parses as strict JSON after a clean close; a killed process
        leaves the unterminated form Perfetto still accepts."""
        with self._lock:
            if self._closed:
                return
            self._flush_locked()
            self._closed = True
            try:
                if terminate:
                    self._f.write("{}]\n")  # trailing {} absorbs the comma  # threadlint: ok[CL003] the terminator must serialize with in-flight flushes — close IS the last write
                self._f.close()
            except (OSError, ValueError):
                pass


# ---------------------------------------------------------------------------
# span aggregation (profiler.summary + reconciliation)

_stats_lock = threading.Lock()
# (cat, name) -> [count, total_s, self_s]
_stats = {}


def _note(cat, name, dur_s, self_s):
    with _stats_lock:
        ent = _stats.get((cat, name))
        if ent is None:
            _stats[(cat, name)] = [1, dur_s, self_s]
        else:
            ent[0] += 1
            ent[1] += dur_s
            ent[2] += self_s


def span_stats():
    """{(cat, name): {count, total_s, self_s}} — in-process aggregate
    of every span recorded since configure/reset (kill switch off =
    nothing accumulates)."""
    with _stats_lock:
        return {k: {"count": v[0], "total_s": v[1], "self_s": v[2]}
                for k, v in _stats.items()}


def phase_totals():
    """{cat: total wall seconds} over recorded spans — the per-phase
    decomposition bench.py persists as ``*_phase_s``."""
    out = {}
    with _stats_lock:
        for (cat, _name), v in _stats.items():
            out[cat] = out.get(cat, 0.0) + v[2]  # self time: no double count
    return out


def reset_span_stats():
    with _stats_lock:
        _stats.clear()


def summary_lines(top=5):
    """Human lines for profiler.summary: top spans by SELF time (the
    time a phase spent in its own code, children excluded)."""
    st = span_stats()
    if not st:
        return []
    rows = sorted(st.items(), key=lambda kv: -kv[1]["self_s"])[:top]
    lines = ["span timeline: " + ", ".join(
        f"{cat}: {tot:.3f}s" for cat, tot in
        sorted(phase_totals().items(), key=lambda kv: -kv[1])[:6])]
    lines.append("  top spans (self time): " + ", ".join(
        f"{cat}/{name}: {v['self_s']:.3f}s x{v['count']}"
        for (cat, name), v in rows))
    t = _tracer
    if t is not None:
        n = t.emitted + len(t._buf)  # + the not-yet-flushed tail
        lines.append(f"  trace file: {t.path} ({n} events"
                     + (f", {t.dropped} dropped" if t.dropped else "") + ")")
    return lines


# ---------------------------------------------------------------------------
# the producer API

class _NullSpan:
    """Shared zero-cost context manager returned while tracing is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "cat", "args", "_w0", "_t0", "_child")

    def __init__(self, name, cat, args):
        self.name = name
        self.cat = cat
        self.args = args
        self._child = 0.0

    def __enter__(self):
        st = _tl.stack
        if st is None:
            st = _tl.stack = []
        st.append(self)
        self._w0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0
        st = _tl.stack
        if st and st[-1] is self:
            st.pop()
        if st:
            st[-1]._child += dur
        t = _tracer
        if t is not None and _live[0]:
            t.emit_complete(self.name, self.cat, self._w0, dur, self.args)
            _note(self.cat, self.name, dur, max(0.0, dur - self._child))
        fr = _fr[0]
        if fr is not None:
            fr("span", self.cat, self.name, self._w0, dur, self.args)
        return False


def span(name, cat="runtime", /, **args):
    """Context manager recording one complete span (nested spans
    subtract from the parent's self time). Returns a shared no-op when
    tracing is off — producers may call this unconditionally on warm
    paths; truly hot paths should guard with ``tracing._on[0]``."""
    if not _on[0]:
        return _NULL
    return _Span(name, cat, args or None)


def set_span_arg(sp, key, value):
    """Attach/overwrite one arg on a live span returned by `span()`
    (no-op for the disabled null span) — for attributes only known by
    the time the region ends, like a flush's executed mode."""
    if isinstance(sp, _Span):
        if sp.args is None:
            sp.args = {}
        sp.args[key] = value


def emit_span(name, cat, wall_start, dur_s, /, **args):
    """Record a span measured EXTERNALLY (the producer already timed
    the operation for a metrics counter/histogram — emitting from the
    same numbers is what makes span/metric reconciliation exact). No
    nesting bookkeeping: self time == total time."""
    if not _on[0]:
        return
    t = _tracer
    if t is not None and _live[0]:
        t.emit_complete(name, cat, wall_start, dur_s, args or None)
        _note(cat, name, dur_s, dur_s)
    fr = _fr[0]
    if fr is not None:
        fr("span", cat, name, wall_start, dur_s, args or None)


def instant(name, cat="runtime", /, **args):
    """One instant event (a point on the timeline: a stall detection, a
    demotion) — no duration, not part of span stats."""
    if not _on[0]:
        return
    t = _tracer
    if t is not None and _live[0]:
        t.emit_instant(name, cat, args or None)
    fr = _fr[0]
    if fr is not None:
        fr("instant", cat, name, 0.0, 0.0, args or None)


# ---------------------------------------------------------------------------
# configuration

def configure(directory=None, flush_every=None, max_events=None):
    """Point the tracer at `directory` (default: ``PADDLE_TPU_TRACE``).
    Returns the effective directory, or None when tracing stays off.
    The per-process file is ``trace-<host>-<pid>.json`` so multiple
    ranks sharing one directory never collide. Reconfiguring to a new
    directory closes (and terminates) the old file."""
    global _tracer
    directory = directory or os.environ.get("PADDLE_TPU_TRACE")
    if not directory or directory.lower() in ("0", "false", "no"):
        return None
    directory = os.path.abspath(directory)
    # hostname/pid resolved BEFORE the config lock (gethostname can
    # block on a slow resolver; nothing under the lock should)
    path = os.path.join(
        directory,
        f"{TRACE_BASENAME_PREFIX}{socket.gethostname()}-"
        f"{os.getpid()}.json")
    with _lock:
        # an explicit configure IS an opt-in: it overrides a previous
        # set_enabled(False) kill (tests and bench rely on this)
        _killed[0] = False
        if _config["dir"] == directory and _tracer is not None:
            # same dir: honor newly requested bounds in place (an early
            # return that dropped them would leave a caller believing
            # in per-span durability the buffer doesn't provide)
            if flush_every is not None:
                _tracer.flush_every = max(1, int(flush_every))
            if max_events is not None:
                _tracer.max_events = max(1, int(max_events))
            _live[0] = True
            _recompute_on()
            return directory
        new = SpanTracer(path, flush_every=flush_every,
                         max_events=max_events)
        old = _tracer
        _tracer = new
        _config["dir"] = directory
        _live[0] = True
        _recompute_on()
    if old is not None:
        old.close()
    return directory


def enabled():
    """True while FILE tracing is live (a tracer is configured and the
    kill switch is on) — the flight-recorder tap does not count; see
    diagnostics.enabled() for that layer's switch."""
    return _live[0]


def set_enabled(mode):
    """Runtime kill switch for file tracing: False stops trace-file
    writes and span-stats accumulation (the buffer is flushed so
    nothing recorded is lost); True re-arms a configured tracer. The
    flight-recorder tap (diagnostics) is governed by its own switch.
    Returns the previous state."""
    prev = _live[0]
    _killed[0] = not mode  # threadlint: ok[CL001] GIL-atomic flag publish; config-time single-writer, readers tolerate either value (same contract as dispatch.set_warmup_count)
    if mode:
        _live[0] = _tracer is not None  # threadlint: ok[CL001] see above
    else:
        _live[0] = False  # threadlint: ok[CL001] see above
        t = _tracer
        if t is not None:
            t.flush()
    _recompute_on()
    return prev


def trace_dir():
    return _config["dir"]


def trace_path():
    t = _tracer
    return t.path if t is not None else None


def tracer():
    return _tracer


def flush():
    t = _tracer
    if t is not None:
        t.flush()


def close():
    """Flush + terminate the trace file (registered atexit; a killed
    process skips this and leaves the Perfetto-tolerated open array)."""
    t = _tracer
    if t is not None:
        t.close()


atexit.register(close)


# ---------------------------------------------------------------------------
# reading / validation (tests, smoke, merge)

def read_trace(path, strict=False):
    """Parse a trace file back into its event list. Tolerates the
    kill -9 shape: missing ``]`` terminator and a torn final line.
    With `strict`, any malformed NON-final line raises ValueError —
    the Chrome-format validity check the tests gate on."""
    with open(path) as f:
        raw = f.read()
    stripped = raw.strip()
    if not stripped.startswith("["):
        raise ValueError(f"{path}: not a Chrome trace array")
    if stripped.endswith("]"):
        return [e for e in json.loads(stripped) if e]  # drop the {} pad
    events = []
    lines = stripped[1:].splitlines()
    for i, line in enumerate(lines):
        line = line.strip().rstrip(",")
        if not line:
            continue
        try:
            events.append(json.loads(line))
        except ValueError:
            if strict and i < len(lines) - 1:
                raise ValueError(f"{path}: malformed trace line {i + 2}")
            continue  # torn tail line (the kill -9 contract)
    return events


def validate_trace(path):
    """Chrome Trace Event Format validity: every event parses and
    carries the required keys for its phase. Returns the events;
    raises ValueError on a violation."""
    events = read_trace(path, strict=True)
    for e in events:
        ph = e.get("ph")
        if ph not in ("X", "M", "i", "B", "E"):
            raise ValueError(f"{path}: unknown phase {ph!r} in {e}")
        for k in ("name", "pid", "tid"):
            if k not in e:
                raise ValueError(f"{path}: event missing {k!r}: {e}")
        if ph == "X":
            if not isinstance(e.get("ts"), int) or \
                    not isinstance(e.get("dur"), int) or e["dur"] < 0:
                raise ValueError(f"{path}: bad X event timing: {e}")
    return events


# ---------------------------------------------------------------------------
# reconciliation: the timeline and the counters must agree

# the serving access log registers its aggregate snapshot here at
# import (inference/access_log.py) — a probe function, not an import,
# so the runtime layer never depends on the inference package
_serve_access_probe = [None]


def set_serve_access_probe(fn):
    """Register (or clear, with None) the access-log aggregate probe
    `reconcile_with_metrics` compares against the serve counters.
    Returns the previous probe."""
    prev = _serve_access_probe[0]
    _serve_access_probe[0] = fn  # threadlint: ok[CL001] GIL-atomic publish; import-time single-writer
    return prev


def reconcile_with_metrics(tolerance=0.02, abs_slack=2e-3):
    """Assert the span sums agree with the authoritative counters.
    Producers emit these spans from the SAME measured duration that
    feeds the metric, so agreement is exact up to float accumulation —
    `tolerance` (relative) and `abs_slack` (seconds) absorb only that.

    Checked pairs (each skipped when neither side saw traffic):

    * ``dispatch/run:*`` spans      vs ``dispatch_stats()["per_op"][*]["run_s"]``
    * ``step/train_step`` spans     vs ``paddle_tpu_step_seconds`` histogram
    * ``data/data_wait`` spans      vs ``paddle_tpu_data_wait_seconds`` histogram
    * ``io/h2d`` spans              vs ``paddle_tpu_h2d_seconds`` histogram
    * ``checkpoint/save`` spans     vs ``paddle_tpu_checkpoint_save_seconds``
    * ``checkpoint/restore`` spans  vs ``paddle_tpu_checkpoint_restore_seconds``
    * ``serve/request`` spans       vs ``paddle_tpu_serve_request_seconds``
    * ``serve/ttft`` spans          vs ``paddle_tpu_serve_ttft_seconds``

    Access-log checks (when inference/access_log.py has registered its
    probe): per-outcome record counts must equal the
    ``paddle_tpu_serve_requests_total`` series EXACTLY, and the
    record-aggregated latency/TTFT sums must match the serve
    histograms — records are built from the same measured values, so
    only float accumulation order separates the two surfaces.

    Returns (ok, report) where report maps check name ->
    {span_s, metric_s, span_n, metric_n, ok, skipped}."""
    st = span_stats()
    snap = _telemetry.snapshot()

    def spans(cat, name=None, prefix=None):
        tot = n = 0.0
        for (c, nm), v in st.items():
            if c != cat:
                continue
            if name is not None and nm != name:
                continue
            if prefix is not None and not nm.startswith(prefix):
                continue
            tot += v["total_s"]
            n += v["count"]
        return tot, int(n)

    def hist(name):
        fam = snap.get(name)
        if not fam or not fam.get("series"):
            return 0.0, 0
        s = fam["series"][0]
        return float(s.get("sum", 0.0)), int(s.get("count", 0))

    report = {}

    def check(key, span_pair, metric_pair, count_exact=True):
        (ss, sn), (ms, mn) = span_pair, metric_pair
        skipped = sn == 0 and mn == 0
        ok = skipped or (
            (not count_exact or sn == mn)
            and abs(ss - ms) <= max(abs_slack, tolerance * max(ss, ms)))
        report[key] = {"span_s": ss, "metric_s": ms, "span_n": sn,
                       "metric_n": mn, "ok": ok, "skipped": skipped}

    try:
        from ..core.dispatch import dispatch_stats

        ds = dispatch_stats()
        run_s = sum(o.get("run_s", 0.0) for o in ds["per_op"].values())
        run_n = sum(o.get("run_samples", 0) for o in ds["per_op"].values())
        check("dispatch_run", spans("dispatch", prefix="run:"),
              (run_s, run_n))
    except Exception:  # pragma: no cover — jax-less context
        pass
    check("step", spans("step", name="train_step"),
          hist("paddle_tpu_step_seconds"))
    check("data_wait", spans("data", name="data_wait"),
          hist("paddle_tpu_data_wait_seconds"))
    check("h2d", spans("io", name="h2d"),
          hist("paddle_tpu_h2d_seconds"))
    check("checkpoint_save", spans("checkpoint", name="save"),
          hist("paddle_tpu_checkpoint_save_seconds"))
    check("checkpoint_restore", spans("checkpoint", name="restore"),
          hist("paddle_tpu_checkpoint_restore_seconds"))
    check("serve_request", spans("serve", name="request"),
          hist("paddle_tpu_serve_request_seconds"))
    check("serve_ttft", spans("serve", name="ttft"),
          hist("paddle_tpu_serve_ttft_seconds"))
    probe = _serve_access_probe[0]
    acc = None
    if probe is not None:
        try:
            acc = probe()
        except Exception:  # noqa: BLE001 — a broken probe skips, not fails
            acc = None
    if acc is not None:
        fam = snap.get("paddle_tpu_serve_requests_total") or {}
        counts = {}
        for s in fam.get("series", []):
            key = s.get("labels", {}).get("outcome")
            counts[key] = counts.get(key, 0) + int(s.get("value", 0))
        a_out = {k: int(v) for k, v in acc.get("outcomes", {}).items()}
        n_acc = sum(a_out.values())
        n_met = sum(counts.values())
        skipped = n_acc == 0 and n_met == 0
        per_outcome_ok = all(
            counts.get(k, 0) == a_out.get(k, 0)
            for k in set(counts) | set(a_out))
        report["serve_access_outcomes"] = {
            "span_s": 0.0, "metric_s": 0.0, "span_n": n_acc,
            "metric_n": n_met, "ok": skipped or per_outcome_ok,
            "skipped": skipped}
        check("serve_access_latency",
              (acc.get("latency_sum", 0.0),
               int(acc.get("latency_count", 0))),
              hist("paddle_tpu_serve_request_seconds"))
        check("serve_access_ttft",
              (acc.get("ttft_sum", 0.0), int(acc.get("ttft_count", 0))),
              hist("paddle_tpu_serve_ttft_seconds"))
    ok = all(v["ok"] for v in report.values())
    return ok, report


# ---------------------------------------------------------------------------
# process wiring: env-driven auto-config (the zero-user-code promise —
# a plain Model.fit under PADDLE_TPU_TRACE produces a complete timeline)

if os.environ.get("PADDLE_TPU_TRACE"):
    try:
        configure()
    except Exception:  # pragma: no cover — never break import
        pass
