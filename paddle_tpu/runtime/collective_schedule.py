"""Per-rank collective-schedule recorder.

The SPMD contract says every rank issues the same collectives in the
same order. When a rank breaks it, the only runtime symptom today is a
wedge: the conforming ranks sit inside a collective until the cluster
watchdog's dead-peer deadline names the wrong thing ("peer dead") for
the wrong reason. This module gives the contract a runtime witness:
the collective layer calls `note()` per issued collective, and the
recorder keeps

* a monotonically increasing sequence number and a rolling digest
  chained over (op, axis, aval) — two ranks with the same schedule
  have the same digest at the same seq;
* **window marks** — every MARK_WINDOW entries the (seq, digest) pair
  is latched. Marks are positional, so ranks heartbeating at
  different rates still share comparable points: any common seq with
  different digests is a divergence, and the FIRST such seq brackets
  where the schedules forked;
* a bounded tail of recent entries and a bounded per-site counter for
  the postmortem diff and --verify-runtime cross-referencing.

Publication rides the existing heartbeat path (ElasticManager.tick
merges `heartbeat_payload()` into the cluster heartbeat record);
ClusterMonitor compares peers' marks and raises a
`collective_divergence` fault with both schedules — seconds after the
fork, not minutes after the deadline.

Pure host bookkeeping: `note()` reads only `.shape`/`.dtype` (served
from memoized avals — never a flush or device sync) and costs a lock
plus one hash. `PADDLE_TPU_COLLECTIVE_SCHEDULE=0` kills it entirely.
"""
from __future__ import annotations

import collections
import hashlib
import os
import sys
import threading

__all__ = [
    "enabled", "note", "schedule_stats", "heartbeat_payload", "reset",
    "MARK_WINDOW",
]

MARK_WINDOW = 16      # entries per digest mark
_MAX_MARKS = 8        # marks kept (covers the last 128 collectives)
_MAX_RECENT = 8       # tail entries kept for diffs
_MAX_SITES = 64       # distinct call sites tracked

_lock = threading.Lock()
_seq = 0
_digest = ""
_marks = collections.deque(maxlen=_MAX_MARKS)    # (seq, digest)
_recent = collections.deque(maxlen=_MAX_RECENT)  # (seq, op, axis, aval, site)
_per_op = {}
_sites = {}


def enabled():
    return os.environ.get(
        "PADDLE_TPU_COLLECTIVE_SCHEDULE", "1").lower() not in (
        "0", "false", "off")


def _aval(shape, dtype):
    if shape is None and dtype is None:
        return "?"
    dims = "x".join(str(d) for d in (shape or ()))
    return f"{dtype or '?'}[{dims}]"


def _call_site():
    """`paddle_tpu/...:line` of the innermost in-tree caller — skipping
    the recorder and the collective layer itself. A driver script
    calling collectives directly has no in-tree caller frame; the
    collective-layer frame is the fallback, so the site always lands
    inside the tree --verify-runtime analyzes."""
    try:
        frame = sys._getframe(2)
    except ValueError:  # pragma: no cover
        return "?"
    fallback = None
    depth = 0
    while frame is not None and depth < 16:
        fname = frame.f_code.co_filename
        norm = fname.replace(os.sep, "/")
        if norm.endswith("collective_schedule.py"):
            frame = frame.f_back
            depth += 1
            continue
        idx = norm.rfind("paddle_tpu/")
        if idx >= 0:
            rel = norm[idx:]
            site = f"{rel}:{frame.f_lineno}"
            if rel.endswith("distributed/collective.py"):
                # keep overwriting: the OUTERMOST collective-layer frame
                # is the public op the external caller invoked (inner
                # frames are private helpers)
                fallback = site
            else:
                return site
        frame = frame.f_back
        depth += 1
    return fallback or "?"


def note(op, axis="", shape=None, dtype=None):
    """Record one issued collective. Cheap, lock-guarded, allocation-
    light; a no-op when the recorder is killed."""
    if not enabled():
        return
    aval = _aval(shape, dtype)
    site = _call_site()
    entry = f"{op}:{axis}:{aval}"
    global _seq, _digest
    with _lock:
        _seq += 1
        _digest = hashlib.sha1(
            (_digest + "|" + entry).encode()).hexdigest()[:12]
        _recent.append((_seq, op, axis, aval, site))
        _per_op[op] = _per_op.get(op, 0) + 1
        if len(_sites) < _MAX_SITES or site in _sites:
            _sites[site] = _sites.get(site, 0) + 1
        else:
            _sites["<overflow>"] = _sites.get("<overflow>", 0) + 1
        if _seq % MARK_WINDOW == 0:
            _marks.append((_seq, _digest))


def schedule_stats():
    """The dispatch_stats()["collectives"] view."""
    with _lock:
        return {
            "enabled": enabled(),
            "seq": _seq,
            "fingerprint": _digest,
            "per_op": dict(sorted(_per_op.items())),
            "marks": [list(m) for m in _marks],
            "recent": [list(r) for r in _recent],
            "sites": dict(sorted(_sites.items())),
        }


def heartbeat_payload():
    """Compact per-heartbeat publication: current (seq, fp), the
    window marks, and a short schedule tail for the divergence diff.
    Empty when killed or before the first collective."""
    if not enabled():
        return {}
    with _lock:
        if _seq == 0:
            return {}
        return {"csched": {
            "seq": _seq,
            "fp": _digest,
            "marks": [list(m) for m in _marks],
            "tail": [list(r) for r in _recent],
        }}


def reset():
    global _seq, _digest
    with _lock:
        _seq = 0
        _digest = ""
        _marks.clear()
        _recent.clear()
        _per_op.clear()
        _sites.clear()
