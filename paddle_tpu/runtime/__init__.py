"""paddle_tpu.runtime — host-side runtime services around the compute
path: staging buffers (`staging`), HBM stats (`memory`), the
fault-tolerance substrate (`resilience`), the warm-start subsystem
(`warmup`: persistent compile cache + shape-manifest AOT precompile),
the unified telemetry layer (`telemetry`: metrics registry +
structured event stream + exporters), span tracing (`tracing`), and
the crash-and-hang layer (`diagnostics`: flight recorder, postmortem
bundles, /statusz).

`telemetry`, `resilience`, `tracing` and `diagnostics` are imported
eagerly (stdlib[+numpy], cheap; `core.dispatch` depends on the first
three, and diagnostics must arm its flight-recorder taps before any
producer runs); `warmup` loads with the dispatch layer,
`memory`/`staging` stay import-on-use.
"""
from . import telemetry  # noqa: F401
from . import resilience  # noqa: F401
from . import tracing  # noqa: F401
from . import diagnostics  # noqa: F401

__all__ = ["telemetry", "resilience", "tracing", "diagnostics",
           "warmup", "memory", "staging"]
