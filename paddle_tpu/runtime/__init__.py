"""paddle_tpu.runtime — host-side runtime services around the compute
path: staging buffers (`staging`), HBM stats (`memory`), the
fault-tolerance substrate (`resilience`), the warm-start subsystem
(`warmup`: persistent compile cache + shape-manifest AOT precompile),
and the unified telemetry layer (`telemetry`: metrics registry +
structured event stream + exporters).

Only `telemetry` and `resilience` are imported eagerly (stdlib[+numpy],
cheap, and `core.dispatch` depends on both); `warmup` loads with the
dispatch layer, `memory`/`staging` stay import-on-use.
"""
from . import telemetry  # noqa: F401
from . import resilience  # noqa: F401

__all__ = ["telemetry", "resilience", "warmup", "memory", "staging"]
