"""paddle_tpu.runtime — host-side runtime services around the compute
path: staging buffers (`staging`), HBM stats (`memory`), the
fault-tolerance substrate (`resilience`), and the warm-start subsystem
(`warmup`: persistent compile cache + shape-manifest AOT precompile).

Only `resilience` is imported eagerly (stdlib+numpy, cheap, and
`core.dispatch` depends on it); `warmup` loads with the dispatch layer,
`memory`/`staging` stay import-on-use.
"""
from . import resilience  # noqa: F401

__all__ = ["resilience", "warmup", "memory", "staging"]
