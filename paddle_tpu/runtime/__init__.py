"""paddle_tpu.runtime — host-side runtime services around the compute
path: staging buffers (`staging`), HBM stats (`memory`), and the
fault-tolerance substrate (`resilience`).

Only `resilience` is imported eagerly (stdlib+numpy, cheap, and
`core.dispatch` depends on it); `memory`/`staging` stay import-on-use.
"""
from . import resilience  # noqa: F401

__all__ = ["resilience", "memory", "staging"]
