"""paddle.cost_model — per-op/time cost estimation.

Reference: python/paddle/cost_model/cost_model.py:23 (CostModel:
build_program, profile_measure over the C++ profiler, static_cost_data
from a shipped GPU benchmark JSON, get_static_op_time).

TPU-native: instead of a stale benchmark table, op costs come from XLA
itself — `profile_measure` compiles the program and reads the compiled
HLO cost analysis (exact FLOPs/bytes) plus a measured wall-time;
`get_static_op_time` measures the op live on the attached backend once and
memoizes. Same API, better numbers.
"""
from __future__ import annotations

import time

import numpy as np

__all__ = ["CostModel"]


class CostModel:
    def __init__(self):
        self._static_cost_data = None
        self._op_time_cache = {}

    def build_program(self):
        import paddle_tpu as paddle

        paddle.enable_static()
        main_program = paddle.static.Program()
        startup_program = paddle.static.Program()
        with paddle.static.program_guard(main_program, startup_program):
            data = paddle.static.data(name="X", shape=[10, 1],
                                      dtype="float32")
            hidden = paddle.static.nn.fc(data, 10)
            self._loss = paddle.mean(hidden)
            self._built_main = main_program
        return startup_program, main_program

    def profile_measure(self, startup_program, main_program, device="tpu",
                        fetch_cost_list=("time",)):
        """Compile + run the program; returns {"time": steady-state wall
        ms, "flops": XLA cost-analysis FLOPs, "bytes accessed": ...}."""
        import paddle_tpu as paddle

        exe = paddle.static.Executor()
        exe.run(startup_program)
        feed = {"X": paddle.to_tensor(
            np.random.random((10, 1)).astype(np.float32))}
        # only fetch the loss var for OUR toy program — arbitrary caller
        # programs don't contain it
        fetch = [self._loss] if main_program is getattr(
            self, "_built_main", None) else []
        exe.run(main_program, feed=feed, fetch_list=fetch)  # warmup/compile
        t0 = time.perf_counter()
        out = exe.run(main_program, feed=feed, fetch_list=fetch)
        if out:  # fetched values are np arrays: the run is synced
            np.asarray(out[0])
        cost = {"time": (time.perf_counter() - t0) * 1e3}
        cost.update(exe.last_cost_analysis() or {})
        return cost

    _MEASURABLE = ("matmul", "relu", "softmax", "elementwise_add", "mean")

    def static_cost_data(self):
        """Reference loads static_op_benchmark.json (A100 timings, keys
        paddle_gpu_time / paddle_gpu_time_backward); here the same-shaped
        table is assembled lazily from live measurements on the attached
        backend."""
        if self._static_cost_data is None:
            self._static_cost_data = [
                {"op": name, "config": f"dtype: float32",
                 "paddle_gpu_time": self._measure(name, True, "float32"),
                 "paddle_gpu_time_backward": self._measure(name, False,
                                                           "float32")}
                for name in ("matmul", "relu", "softmax")]
        return self._static_cost_data

    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        """Returns {"op_time": ms, "config": ...} as the reference does, or
        an empty dict for ops with no measurement recipe."""
        if op_name is None:
            raise ValueError(
                "op_name should not be empty when you want to get static "
                "op time")
        if op_name not in self._MEASURABLE:
            return {}
        return {"op_time": self._measure(op_name, forward, dtype),
                "config": f"dtype: {dtype}"}

    def _measure(self, op_name, forward, dtype):
        key = (op_name, forward, dtype)
        if key in self._op_time_cache:
            return self._op_time_cache[key]
        import jax
        import jax.numpy as jnp

        x = jnp.ones((256, 256), dtype)
        ops = {
            "matmul": lambda v: v @ v,
            "relu": lambda v: jnp.maximum(v, 0),
            "softmax": lambda v: jax.nn.softmax(v, -1),
            "elementwise_add": lambda v: v + v,
            "mean": lambda v: v.mean(),
        }
        fn = ops[op_name]
        target = (jax.jit(jax.grad(lambda v: fn(v).sum())) if not forward  # tracelint: ok[suspend-audit] raw-jnp microbench lambdas
                  else jax.jit(fn))  # tracelint: ok[suspend-audit] raw-jnp microbench lambdas
        target(x).block_until_ready()  # compile
        t0 = time.perf_counter()
        for _ in range(10):
            out = target(x)
        jax.tree_util.tree_leaves(out)[0].block_until_ready()
        ms = (time.perf_counter() - t0) / 10 * 1e3
        self._op_time_cache[key] = ms
        return ms
