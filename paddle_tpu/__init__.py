"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's API.

Compute path: JAX/XLA (MXU-shaped, bf16-first) + Pallas kernels for fused hot
ops. Runtime: eager autograd tape over jit-cached XLA executables; blessed
paths (hapi Model, static Executor, jit.to_static) compile whole steps into
single XLA programs.

Usage: `import paddle_tpu as paddle` — the namespace mirrors `paddle.*`.
"""
from __future__ import annotations

import jax as _jax
import numpy as _np

# NOTE: importing this library does NOT flip jax_enable_x64 (round-2 verdict
# weak #3: a global x64 default risks f64 on every non-blessed TPU path).
# CPU-hosted numerics tests opt in via tests/conftest.py; on TPU the library
# runs with JAX's default 32-bit types — int64/float64 dtype requests are
# honored when x64 is on and degrade to 32-bit otherwise, matching JAX.

from .core import dtype as _dtype_mod
from .core.dtype import (  # noqa: F401
    bfloat16, bool, complex64, complex128, dtype, finfo, float16, float32,
    float64, get_default_dtype, iinfo, int8, int16, int32, int64,
    set_default_dtype, uint8,
)
from .core.tensor import Tensor  # noqa: F401
from .core import autograd as _autograd
from .core.autograd import no_grad, enable_grad, set_grad_enabled, is_grad_enabled  # noqa: F401
from .core.autograd import grad  # noqa: F401

from . import tensor as tensor  # noqa: F401
from .tensor import _register_methods as _rm

_rm()

from .tensor import *  # noqa: F401,F403
from .tensor import to_tensor  # noqa: F401

from .framework import (  # noqa: F401
    disable_static, enable_static, in_dynamic_mode, in_dygraph_mode, seed,
    get_rng_state, set_rng_state,
)
from .framework.debug import check_numerics, set_printoptions  # noqa: F401
from .framework.random import get_cuda_rng_state, set_cuda_rng_state  # noqa: F401

from . import fft  # noqa: F401
from . import linalg  # noqa: F401
from . import autograd  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import regularizer  # noqa: F401
from .framework.param_attr import ParamAttr  # noqa: F401
from .nn.layer.layers import create_parameter  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import hapi  # noqa: F401
from .hapi import Model, callbacks, summary  # noqa: F401
from .hapi.flops import flops  # noqa: F401
from .framework.io import load, save  # noqa: F401
from . import vision  # noqa: F401
from . import text  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import static  # noqa: F401
from . import jit  # noqa: F401
from . import distributed  # noqa: F401
from . import inference  # noqa: F401
from . import distribution  # noqa: F401
from . import sparse  # noqa: F401
from . import utils  # noqa: F401
from . import incubate  # noqa: F401
from . import onnx  # noqa: F401
from . import cost_model  # noqa: F401
from . import dataset  # noqa: F401
from . import hub  # noqa: F401
from . import reader  # noqa: F401
from . import sysconfig  # noqa: F401
from . import profiler  # noqa: F401
from . import quantization  # noqa: F401
from . import signal  # noqa: F401
from . import device  # noqa: F401
from .device import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, CustomPlace, IPUPlace, MLUPlace, NPUPlace,
    TPUPlace, XPUPlace, get_cudnn_version, get_device, is_compiled_with_cinn,
    is_compiled_with_cuda, is_compiled_with_ipu, is_compiled_with_mlu,
    is_compiled_with_npu, is_compiled_with_rocm, is_compiled_with_xpu,
    set_device,
)
from .distributed.parallel import DataParallel  # noqa: F401
from .static.program import InputSpec  # noqa: F401

from . import version  # noqa: F401
from .version import full_version as __version__  # noqa: F401

_FLAGS = {}


def set_flags(flags):
    """paddle.set_flags — gflags shim; XLA owns runtime tuning on TPU, so
    flags are recorded for get_flags symmetry only."""
    _FLAGS.update(flags)


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    return {f: _FLAGS.get(f) for f in flags}


def disable_signal_handler():
    """No-op: the reference installs C++ fatal-signal dumpers; the JAX
    runtime doesn't hook signals in the first place."""


def batch(reader, batch_size, drop_last=False):
    """Legacy reader decorator (reference fluid/io.py batch)."""

    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_tpu():
    return True


def is_compiled_with_cinn():
    return False


def is_compiled_with_mkldnn():
    return False


def is_compiled_with_distribute():
    return True


def tolist(x):
    """paddle.tolist (reference: tensor/manipulation.py:254) — alias of
    Tensor.tolist."""
    return x.tolist() if hasattr(x, "tolist") else list(x)


def check_shape(shape):
    """Validate a shape argument before creation ops (reference:
    fluid/layers/utils.py:373)."""
    if hasattr(shape, "_value") or hasattr(shape, "dtype"):
        return  # shape-as-tensor: dtype validated at trace time
    for ele in shape:
        if hasattr(ele, "_value"):
            continue
        if not isinstance(ele, (int, _np.integer)):
            raise TypeError(
                "All elements in ``shape`` must be integers when it's a "
                "list or tuple")
        if ele < 0:
            raise ValueError(
                "All elements in ``shape`` must be positive when it's a "
                "list or tuple")

from . import fluid  # noqa: F401,E402  (reference-era compat namespace)
from . import compat  # noqa: F401,E402
from . import _C_ops  # noqa: F401,E402
