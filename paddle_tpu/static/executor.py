"""Executor (reference: python/paddle/fluid/executor.py).

run(program, feed, fetch_list) jit-compiles the recorded graph once per feed
shape signature and replays it as a single XLA program. Training programs
(optimizer.minimize recorded) carry functional optimizer state inside the
Executor and donate param buffers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .program import Program, default_main_program

__all__ = ["Executor", "global_scope", "scope_guard", "CompiledProgram",
           "BuildStrategy", "ExecutionStrategy"]


class _Scope:
    def __init__(self):
        self.vars = {}

    def find_var(self, name):
        return self.vars.get(name)

    def var(self, name):
        return self.vars.setdefault(name, _ScopeVar(name))


class _ScopeVar:
    def __init__(self, name):
        self.name = name
        self.value = None

    def get_tensor(self):
        return self.value


_scope = _Scope()


def global_scope():
    return _scope


import contextlib


@contextlib.contextmanager
def scope_guard(scope):
    global _scope
    prev = _scope
    _scope = scope
    try:
        yield
    finally:
        _scope = prev


class BuildStrategy:
    def __init__(self):
        self.memory_optimize = True
        self.enable_inplace = True
        self.fuse_all_optimizer_ops = True  # XLA fuses by construction


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100


class CompiledProgram:
    """reference: fluid/compiler.py — here programs always compile whole, so
    this is a thin marker carrying build strategies."""

    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        return self


class Executor:
    def __init__(self, place=None):
        self.place = place
        self._cache = {}      # (prog id, shape sig, fetch sig, train) -> fn
        self._opt_states = {}  # prog id -> functional opt states
        self._aval_cache = {}  # sig -> abstract arg shapes (diagnostics)
        self._ran_startup = False

    def run(self, program=None, feed=None, fetch_list=None, feed_var_name="feed",
            fetch_var_name="fetch", scope=None, return_numpy=True,
            use_program_cache=True):
        if isinstance(program, CompiledProgram):
            program = program.program
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        feed_vals = {}
        for name, v in program.feed_vars.items():
            if name not in feed:
                raise ValueError(f"missing feed for static.data {name!r}")
            fv = feed[name]
            fv = fv._value if isinstance(fv, Tensor) else jnp.asarray(
                np.asarray(fv))
            feed_vals[name] = fv
        fetch_ids = []
        fetch_tensors = []
        for f in fetch_list:
            t = f
            if isinstance(t, str):
                raise TypeError("fetch by name unsupported; pass the Tensor")
            fetch_ids.append(id(t))
            fetch_tensors.append(t)

        train = bool(program.minimize_records)
        sig = (id(program),
               tuple((n, tuple(v.shape), str(v.dtype))
                     for n, v in sorted(feed_vals.items())),
               tuple(fetch_ids), train)
        entry = self._cache.get(sig)
        if entry is None:
            raw = program.build_fn(fetch_ids, train=train)
            if train:
                entry = jax.jit(raw, donate_argnums=(0, 2))  # tracelint: ok[suspend-audit] build_fn replays raw op.fn
            else:
                entry = jax.jit(raw)  # tracelint: ok[suspend-audit] build_fn replays raw op.fn
            self._cache[sig] = entry

        param_vals = {p.name: p._value for p in program.param_ids.values()}

        def _remember_avals(*trees):
            # once per cache signature (diagnostic support for
            # last_cost_analysis — must not tax the training hot path)
            if sig not in self._aval_cache:
                self._aval_cache[sig] = jax.tree_util.tree_map(
                    lambda v: jax.ShapeDtypeStruct(jnp.shape(v),
                                                   jnp.result_type(v)),
                    trees)
            self._last_lowerable = (entry, self._aval_cache[sig])

        if train:
            optimizer, _ = program.minimize_records[0]
            states = self._opt_states.get(id(program))
            if states is None:
                states = optimizer.functional_init_states(param_vals)
            lr = jnp.asarray(optimizer.get_lr(), jnp.float32)
            _remember_avals(param_vals, feed_vals, states, lr)
            fetches, new_params, new_states = entry(param_vals, feed_vals,
                                                    states, lr)
            self._opt_states[id(program)] = new_states
            for p in program.param_ids.values():
                p._value = new_params[p.name]
            optimizer._global_step += 1
        else:
            _remember_avals(param_vals, feed_vals)
            fetches, _, _ = entry(param_vals, feed_vals)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    def last_cost_analysis(self):
        """XLA cost analysis (flops, bytes accessed, ...) of the program
        most recently run — exposed for paddle.cost_model. Lowers from the
        recorded abstract shapes; the executable comes from XLA's
        compilation cache, so no duplicate device compile."""
        entry_and_avals = getattr(self, "_last_lowerable", None)
        if entry_and_avals is None:
            return {}
        entry, avals = entry_and_avals
        try:
            cost = entry.lower(*avals).compile().cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0]
            return dict(cost) if cost else {}
        except Exception:  # noqa: BLE001 — diagnostic API, never fatal
            return {}

    def close(self):
        self._cache.clear()
