"""paddle.static (reference: python/paddle/static/__init__.py)."""
from . import nn  # noqa: F401
from .executor import (  # noqa: F401
    BuildStrategy, CompiledProgram, ExecutionStrategy, Executor, global_scope,
    scope_guard,
)
from .program import (  # noqa: F401
    InputSpec, Program, Variable, data, default_main_program,
    default_startup_program, name_scope, program_guard,
)


def cpu_places(device_count=None):
    from ..device import CPUPlace

    return [CPUPlace()]


def cuda_places(device_ids=None):  # maps to the accelerator on this build
    from ..device import TPUPlace

    return [TPUPlace(0)]


def save(program, model_path, protocol=4):
    from ..framework.io import save as _save

    params = {p.name: p for p in program.all_parameters()}
    _save(params, model_path + ".pdparams")


def load(program, model_path, executor=None, var_list=None):
    from ..framework.io import load as _load

    params = _load(model_path + ".pdparams")
    for p in program.all_parameters():
        if p.name in params:
            p.set_value(params[p.name])
