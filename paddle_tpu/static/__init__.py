"""paddle.static (reference: python/paddle/static/__init__.py)."""
from . import nn  # noqa: F401
from . import amp  # noqa: F401
from .executor import (  # noqa: F401
    BuildStrategy, CompiledProgram, ExecutionStrategy, Executor, global_scope,
    scope_guard,
)
from .program import (  # noqa: F401
    InputSpec, Program, Variable, data, default_main_program,
    default_startup_program, name_scope, program_guard,
)


def cpu_places(device_count=None):
    from ..device import CPUPlace

    return [CPUPlace()]


def cuda_places(device_ids=None):  # maps to the accelerator on this build
    from ..device import TPUPlace

    return [TPUPlace(0)]


def save(program, model_path, protocol=4):
    from ..framework.io import save as _save

    params = {p.name: p for p in program.all_parameters()}
    _save(params, model_path + ".pdparams")


def load(program, model_path, executor=None, var_list=None):
    from ..framework.io import load as _load

    params = _load(model_path + ".pdparams")
    for p in program.all_parameters():
        if p.name in params:
            p.set_value(params[p.name])


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


def npu_places(device_ids=None):
    return cuda_places(device_ids)


def mlu_places(device_ids=None):
    return cuda_places(device_ids)


# ParallelExecutor: the reference's multi-device executor; this Executor
# already compiles whole programs with XLA (multi-device via Mesh), so the
# parallel variant is the same object behind the legacy ctor signature.
class ParallelExecutor(Executor):
    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        super().__init__()
        self._main_program = main_program

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True):
        return super().run(self._main_program, feed=feed or feed_dict,
                           fetch_list=fetch_list,
                           return_numpy=return_numpy)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..nn.layer.layers import create_parameter as _cp

    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    t = Tensor(jnp.full(tuple(shape), value,
                        __import__("paddle_tpu").core.dtype.to_jax_dtype(
                            dtype)), name=name)
    t.persistable = persistable
    return t


def Print(input, first_n=-1, message=None, summarize=20,  # noqa: A002,N802
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug print op (reference static/nn Print): jax.debug.print inside
    the traced program, identity on the value."""
    import jax

    from ..core.autograd import apply

    def _f(v):
        # user text must not be parsed as a format string
        safe = (message or "").replace("{", "{{").replace("}", "}}")
        jax.debug.print(safe + " {}", v)
        return v

    _f.__name__ = "print_op"
    return apply(_f, input)


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    from .nn import py_func as _pf

    return _pf(func, x, out, backward_func=backward_func,
               skip_vars_in_backward_input=skip_vars_in_backward_input)


def device_guard(device=None):
    """The reference pins ops to a device inside a program; XLA owns
    placement on this backend, so this is a documented no-op scope."""
    import contextlib

    @contextlib.contextmanager
    def _guard():
        yield

    return _guard()


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference static/gradients: build grad expressions eagerly via the
    tape (targets/inputs are recorded tensors)."""
    from ..core.autograd import grad as _grad

    outs = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return _grad(outs, ins, grad_outputs=target_gradients,
                 allow_unused=True)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Reference fluid append_backward: registers the loss for the
    Executor's whole-program backward (optimizer.minimize does this on this
    backend); returns (param, grad_var placeholder) pairs."""
    prog = default_main_program()
    params = parameter_list or prog.all_parameters()
    return [(p, None) for p in params]


def accuracy(input, label, k=1, correct=None, total=None):  # noqa: A002
    # one public op, one behavior: delegate to the traced metric.accuracy
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k, correct=correct, total=total)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,  # noqa: A002
        slide_steps=1):
    """Binned ROC-AUC as a TRACED op (the numpy version concretized at
    static-program build time and baked the dummy-feed result — the
    same failure the accuracy op had). Same histogram binning as
    metric.Auc: predictions bucketed into num_thresholds bins,
    trapezoid over the cumulative TPR/FPR curve."""
    import jax.numpy as jnp

    from ..core.autograd import apply

    T = int(num_thresholds)

    def _f(pred, lab):
        # column 1 = positive-class probability, matching metric.Auc
        # (two-class contract; [N] and [N,1] inputs are raw scores)
        p = pred[:, 1] if pred.ndim == 2 and pred.shape[-1] > 1 \
            else pred.reshape(-1)
        y = lab.reshape(-1).astype(jnp.float32)
        idx = jnp.clip((p * T).astype(jnp.int32), 0, T)
        pos = jnp.zeros(T + 1, jnp.float32).at[idx].add(y)
        neg = jnp.zeros(T + 1, jnp.float32).at[idx].add(1.0 - y)
        # sweep threshold from high to low: cumulative TP/FP counts
        tp = jnp.cumsum(pos[::-1])
        fp = jnp.cumsum(neg[::-1])
        tpr = tp / jnp.maximum(tp[-1], 1e-12)
        fpr = fp / jnp.maximum(fp[-1], 1e-12)
        tpr = jnp.concatenate([jnp.zeros(1), tpr])
        fpr = jnp.concatenate([jnp.zeros(1), fpr])
        return (jnp.diff(fpr) * (tpr[1:] + tpr[:-1]) * 0.5).sum()

    _f.__name__ = "auc"
    val = apply(_f, input, label)
    return val, val, val


# ---- program/state serialization (reference static/io.py) -----------------
def serialize_program(feed_vars, fetch_vars, **kwargs):
    import pickle

    return pickle.dumps(default_main_program())


def deserialize_program(data):
    import pickle

    return pickle.loads(data)


def serialize_persistables(feed_vars, fetch_vars, executor=None, **kwargs):
    import pickle

    import numpy as np

    prog = default_main_program()
    return pickle.dumps({p.name: np.asarray(p._value)
                         for p in prog.all_parameters()})


def deserialize_persistables(program, data, executor=None):
    import pickle

    vals = pickle.loads(data)
    for p in program.all_parameters():
        if p.name in vals:
            p.set_value(vals[p.name])


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    return program


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Reference static/io.py save_inference_model — persists program +
    params; the inference.Predictor and static load both consume it."""
    import pickle

    import os

    prog = program or default_main_program()
    save(prog, path_prefix)
    feeds = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetches = (fetch_vars if isinstance(fetch_vars, (list, tuple))
               else [fetch_vars])
    with open(path_prefix + ".pdmodel.meta", "wb") as f:
        pickle.dump({"feeds": [v.name for v in feeds]}, f)
    # recorded Programs hold live op closures, so fetch targets cannot be
    # re-materialized from disk; keep them for same-process load (the
    # cross-process path rebuilds the program, as the docstring says)
    _inference_fetch_registry[os.path.abspath(path_prefix)] = (
        prog, list(fetches))


_inference_fetch_registry = {}


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns (program, feed_names, fetch_targets) like the reference; the
    program is the caller's recorded Program restored with saved params."""
    import os as _os
    import pickle

    prog, fetches = _inference_fetch_registry.get(
        _os.path.abspath(path_prefix), (default_main_program(), []))
    load(prog, path_prefix)
    meta_path = path_prefix + ".pdmodel.meta"
    try:
        with open(meta_path, "rb") as f:
            meta = pickle.load(f)
        feeds = meta.get("feeds", list(prog.feed_vars))
    except OSError:
        feeds = list(prog.feed_vars)
    return prog, feeds, fetches


def save_program_state(model_path, program=None):
    """Persist the program's parameter state (counterpart of
    load_program_state)."""
    save(program or default_main_program(), model_path)


def load_program_state(model_path, var_list=None):
    from ..framework.io import load as _load

    import numpy as np

    state = _load(model_path + ".pdparams"
                  if not model_path.endswith(".pdparams") else model_path)
    return {k: np.asarray(v._value) if hasattr(v, "_value") else
            np.asarray(v) for k, v in state.items()}


def set_program_state(program, state):
    for p in program.all_parameters():
        if p.name in state:
            p.set_value(state[p.name])


class WeightNormParamAttr:
    """Reference fluid/param_attr.py WeightNormParamAttr — carried through
    to nn.utils.weight_norm on this backend."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


class ExponentialMovingAverage:
    """EMA of parameters (reference static/ExponentialMovingAverage):
    update() accumulates, apply()/restore() swap shadow values in and out."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._shadow = {}
        self._backup = {}
        self._step = 0

    def update(self):
        prog = default_main_program()
        self._step += 1
        # standard bias-corrected dynamic decay
        decay = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in prog.all_parameters():
            prev = self._shadow.get(p.name, p._value)
            self._shadow[p.name] = decay * prev + (1 - decay) * p._value

    import contextlib as _ctx

    @_ctx.contextmanager
    def apply(self, executor=None, need_restore=True):
        prog = default_main_program()
        self._backup = {p.name: p._value for p in prog.all_parameters()}
        for p in prog.all_parameters():
            if p.name in self._shadow:
                p._value = self._shadow[p.name]
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        prog = default_main_program()
        for p in prog.all_parameters():
            if p.name in self._backup:
                p._value = self._backup[p.name]
        self._backup = {}


def ipu_shard_guard(index=-1, stage=-1):
    return device_guard()


class IpuStrategy:
    def __init__(self):
        raise NotImplementedError("IPU backend is not part of this build")


class IpuCompiledProgram:
    def __init__(self, *a, **kw):
        raise NotImplementedError("IPU backend is not part of this build")


# paddle.static.quantization namespace (reference exposes the slim
# quantization passes under paddle.static in 2.4+; the 2.3 tree keeps them
# in fluid/contrib/slim/quantization — same classes either way)
from .. import quantization as quantization  # noqa: E402,F401


# paddle.static.sparsity (reference: python/paddle/static/sparsity —
# re-exports the ASP helpers)
from ..incubate import asp as sparsity  # noqa: E402,F401
