"""paddle.static.nn (reference: python/paddle/static/nn/common.py):
layer-creating functions for program building."""
from __future__ import annotations

from .. import nn as _nn

__all__ = ["fc", "conv2d", "batch_norm", "embedding"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from .. import tensor as T

    in_dim = 1
    for s in x.shape[num_flatten_dims:]:
        in_dim *= s if s > 0 else 1
    layer = _nn.Linear(in_dim, size, weight_attr=weight_attr,
                       bias_attr=bias_attr)
    xin = T.flatten(x, num_flatten_dims) if x.ndim > num_flatten_dims + 1 \
        else x
    out = layer(xin)
    if activation:
        out = getattr(_nn.functional, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,  # noqa: A002
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = _nn.Conv2D(in_ch if in_ch > 0 else 1, num_filters, filter_size,
                       stride, padding, dilation, groups,
                       weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,  # noqa: A002
               bias_attr=None, data_layout="NCHW", in_place=False, name=None,
               moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=True, use_global_stats=False,
               is_test=False):
    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _nn.BatchNorm(ch if ch > 0 else 1, act=act, momentum=momentum,
                          epsilon=epsilon, param_attr=param_attr,
                          bias_attr=bias_attr, data_layout=data_layout,
                          use_global_stats=use_global_stats)
    if is_test:
        layer.eval()
    return layer(input)


def embedding(input, size, is_sparse=False, padding_idx=None,  # noqa: A002
              param_attr=None, dtype="float32"):
    layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                          weight_attr=param_attr)
    return layer(input)
