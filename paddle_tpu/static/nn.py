"""paddle.static.nn (reference: python/paddle/static/nn/common.py +
control_flow.py): layer-creating functions and structured control flow for
program building.

TPU-native control flow: cond/case/switch_case/while_loop lower to
lax.cond/lax.switch/lax.while_loop — compiled control flow inside the one
XLA program, not host branching. Branch callables run with the tape and the
static recorder suspended (the whole construct records as a single traced
op); while_loop threads state explicitly via loop_vars, exactly the shape
XLA wants. Legacy LoD sequence_* ops are intentionally absent (the
reference is retiring LoD; use dense ragged patterns instead).
"""
from __future__ import annotations

import jax
from jax import lax

from .. import nn as _nn
from ..core.tensor import Tensor

__all__ = ["fc", "conv2d", "batch_norm", "embedding",
           "cond", "case", "switch_case", "while_loop",
           "layer_norm", "group_norm", "instance_norm", "spectral_norm",
           "data_norm", "prelu", "conv2d_transpose", "conv3d",
           "conv3d_transpose", "bilinear_tensor_product", "deform_conv2d",
           "row_conv", "py_func"]


def _is_tensor(x):
    return isinstance(x, Tensor)


def _suspended(fn, args=()):
    """Run a user branch callable with tape + static recorder + per-op
    dispatch cache off, returning a pytree of raw jnp values. Closure
    Tensors are handled by the callers: _closure_tensors lifts them to op
    inputs and _rebound swaps in the traced values while the branch runs.
    The dispatch suspend matters for zero-array-input ops inside the
    branch (creation ops): the lax.cond/switch/while trace compiles them
    anyway, so a nested per-op jit entry would only burn cache keys on
    this trace's throwaway avals (tracelint suspend-audit)."""
    from ..core import autograd as ag
    from ..core import dispatch as _dispatch
    from ..nn.layer import layers as _layers

    old = ag._static_recorder
    ag._static_recorder = None
    old_guard = getattr(_layers, "_param_creation_guard", None)
    # a Layer built INSIDE a branch would re-initialize on every replay and
    # never reach the program/optimizer — fail loudly instead of silently
    _layers._param_creation_guard = (
        "creating parameters inside a static.nn control-flow branch is not "
        "supported: build layers outside and call them from the branch")
    try:
        with ag.no_grad(), _dispatch.suspend():  # fuselint: ok[FL004] static-graph recording runs eagerly on dummy values by contract
            out = fn(*[Tensor(a) for a in args])
    finally:
        ag._static_recorder = old
        _layers._param_creation_guard = old_guard
    return jax.tree_util.tree_map(
        lambda t: t._value if _is_tensor(t) else t, out,
        is_leaf=_is_tensor)


def _as_pred(v):
    return v.reshape(()).astype(bool)


def _closure_tensors(*fns):
    """Tensors a branch callable closes over — lifted to explicit op inputs
    so static-program replay rebinds them (they'd otherwise be baked as
    record-time constants) and jit tracing sees real dataflow.

    Closure cells, defaults, and directly-loaded globals are inspected;
    Tensors reached through object attributes (e.g. bound methods reading
    self.weight) or nested containers beyond one level are NOT lifted and
    stay baked at trace time — pass them through lambda closures or
    loop_vars instead."""
    seen = {}
    for fn in fns:
        cells = list(getattr(fn, "__closure__", None) or ())
        vals = [c.cell_contents for c in cells
                if c.cell_contents is not None] \
            + list(getattr(fn, "__defaults__", None) or ())
        # module-level branch fns reach Tensors as globals, not cells:
        # co_names is the exact set of global names the bytecode loads
        code = getattr(fn, "__code__", None)
        g = getattr(fn, "__globals__", None)
        if code is not None and g is not None:
            vals += [g[n] for n in code.co_names if n in g]
        for v in vals:
            items = v if isinstance(v, (list, tuple)) else \
                v.values() if isinstance(v, dict) else [v]
            for item in items:
                if _is_tensor(item) and id(item) not in seen:
                    seen[id(item)] = item
    return list(seen.values())


class _rebound:
    """Temporarily swap dep Tensors' payloads for traced values."""

    def __init__(self, deps, vals):
        self.deps = deps
        self.vals = vals

    def __enter__(self):
        self.saved = [t._value for t in self.deps]
        for t, v in zip(self.deps, self.vals):
            t._value = v

    def __exit__(self, *exc):
        for t, v in zip(self.deps, self.saved):
            t._value = v


def cond(pred, true_fn=None, false_fn=None, name=None):
    """lax.cond over the two branch callables (reference
    static/nn/control_flow.py cond)."""
    from ..core.autograd import apply

    deps = _closure_tensors(true_fn, false_fn)

    def _f(p, *dep_vals):
        with _rebound(deps, dep_vals):
            return lax.cond(_as_pred(p), lambda: _suspended(true_fn),
                            lambda: _suspended(false_fn))

    _f.__name__ = "cond"
    return apply(_f, pred, *deps)


def case(pred_fn_pairs, default=None, name=None):
    """First-match-wins chain of conds."""
    if default is None:
        default = pred_fn_pairs[-1][1]
        pred_fn_pairs = pred_fn_pairs[:-1]
    from ..core.autograd import apply

    deps = _closure_tensors(default, *[f for _, f in pred_fn_pairs])
    n_pred = len(pred_fn_pairs)

    def _f(*args):
        preds, dep_vals = args[:n_pred], args[n_pred:]
        with _rebound(deps, dep_vals):
            out = _suspended(default)
            # fold from the last pair so the FIRST true predicate wins
            for i in range(len(preds) - 1, -1, -1):
                fn = pred_fn_pairs[i][1]
                prev = out
                out = lax.cond(_as_pred(preds[i]),
                               lambda fn=fn: _suspended(fn),
                               lambda prev=prev: prev)
        return out

    _f.__name__ = "case"
    return apply(_f, *[p for p, _ in pred_fn_pairs], *deps)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """lax.switch over indexed branches."""
    from ..core.autograd import apply

    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = [(i, f) for i, f in (branch_fns if isinstance(
            branch_fns[0], (tuple, list)) else enumerate(branch_fns))]
    keys = [int(k) for k, _ in items]
    fns = [f for _, f in items]
    if default is None:
        default = fns[-1]

    deps = _closure_tensors(default, *fns)

    def _f(idx, *dep_vals):
        import jax.numpy as jnp

        idx = idx.reshape(()).astype(jnp.int32)
        # map arbitrary keys onto dense lax.switch positions; unmatched
        # indices take the default branch (last position)
        pos = len(fns)
        for i, k in enumerate(keys):
            pos = jnp.where(idx == k, i, pos)
        with _rebound(deps, dep_vals):
            return lax.switch(pos, [(lambda f=f: _suspended(f))
                                    for f in fns]
                              + [lambda: _suspended(default)])

    _f.__name__ = "switch_case"
    return apply(_f, branch_index, *deps)


def while_loop(cond, body, loop_vars, is_test=False, name=None):  # noqa: A002
    """lax.while_loop with explicitly threaded loop_vars (reference
    static/nn/control_flow.py while_loop). Fully replay-correct: all loop
    state flows through loop_vars. Reverse-mode AD through a dynamic while
    is not supported by XLA — for differentiable loops use a
    static-trip-count construct (e.g. unrolled Python loop or lax.scan via
    nn.RNN), same constraint the TPU compiler imposes everywhere."""
    from ..core.autograd import apply

    deps = _closure_tensors(cond, body)
    n_loop = len(loop_vars)

    def _f(*args):
        vals, dep_vals = args[:n_loop], args[n_loop:]

        def c(vs):
            return _as_pred(_suspended(cond, vs))

        def b(vs):
            out = _suspended(body, vs)
            return tuple(out) if isinstance(out, (list, tuple)) else (out,)

        with _rebound(deps, dep_vals):
            return lax.while_loop(c, b, tuple(vals))

    _f.__name__ = "while_loop"
    out = apply(_f, *loop_vars, *deps)
    return list(out) if isinstance(out, tuple) else out


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from .. import tensor as T

    in_dim = 1
    for s in x.shape[num_flatten_dims:]:
        in_dim *= s if s > 0 else 1
    layer = _nn.Linear(in_dim, size, weight_attr=weight_attr,
                       bias_attr=bias_attr)
    xin = T.flatten(x, num_flatten_dims) if x.ndim > num_flatten_dims + 1 \
        else x
    out = layer(xin)
    if activation:
        out = getattr(_nn.functional, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,  # noqa: A002
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = _nn.Conv2D(in_ch if in_ch > 0 else 1, num_filters, filter_size,
                       stride, padding, dilation, groups,
                       weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,  # noqa: A002
               bias_attr=None, data_layout="NCHW", in_place=False, name=None,
               moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=True, use_global_stats=False,
               is_test=False):
    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _nn.BatchNorm(ch if ch > 0 else 1, act=act, momentum=momentum,
                          epsilon=epsilon, param_attr=param_attr,
                          bias_attr=bias_attr, data_layout=data_layout,
                          use_global_stats=use_global_stats)
    if is_test:
        layer.eval()
    return layer(input)


def embedding(input, size, is_sparse=False, padding_idx=None,  # noqa: A002
              param_attr=None, dtype="float32"):
    layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                          weight_attr=param_attr)
    return layer(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,  # noqa: A002
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    import math as _m

    n = int(_m.prod([s for s in input.shape[begin_norm_axis:]]))
    layer = _nn.LayerNorm(n if n > 0 else 1, epsilon=epsilon,
                          weight_attr=param_attr if scale else False,
                          bias_attr=bias_attr if shift else False)
    from .. import tensor as T

    flat = T.reshape(input, list(input.shape[:begin_norm_axis]) + [n])
    out = T.reshape(layer(flat), input.shape)
    return getattr(_nn.functional, act)(out) if act else out


def group_norm(input, groups, epsilon=1e-5, param_attr=None,  # noqa: A002
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _nn.GroupNorm(groups, ch, epsilon=epsilon,
                          weight_attr=param_attr, bias_attr=bias_attr,
                          data_format=data_layout)
    out = layer(input)
    return getattr(_nn.functional, act)(out) if act else out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,  # noqa: A002
                  name=None):
    layer = _nn.InstanceNorm2D(input.shape[1], epsilon=epsilon,
                               weight_attr=param_attr, bias_attr=bias_attr)
    return layer(input)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,  # noqa: A002
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay=0.9999999,
              enable_scale_and_shift=False):
    """BatchNorm without the learned affine by default (reference
    static/nn/common.py data_norm)."""
    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _nn.BatchNorm(ch if ch > 0 else 1, epsilon=epsilon,
                          param_attr=param_attr if enable_scale_and_shift
                          else False,
                          bias_attr=None if enable_scale_and_shift
                          else False,
                          data_layout=data_layout)
    out = layer(input)
    return getattr(_nn.functional, act)(out) if act else out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """W / sigma_max(W) by power iteration (reference static/nn
    spectral_norm op semantics, stateless)."""
    from ..core.autograd import apply
    import jax.numpy as jnp

    def _f(w):
        mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((mat.shape[0],), mat.dtype) / (mat.shape[0] ** 0.5)
        v = None
        for _ in range(max(1, power_iters)):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ mat @ v
        return w / (sigma + eps)

    _f.__name__ = "spectral_norm"
    return apply(_f, weight)


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    ch = 1 if mode == "all" else (
        x.shape[1] if data_format == "NCHW" else x.shape[-1])
    if mode == "element":
        import math as _m

        ch = int(_m.prod([s for s in x.shape[1:]]))
    layer = _nn.PReLU(num_parameters=ch, weight_attr=param_attr,
                      data_format=data_format)
    return layer(x)


def _derive_transpose_filter(filter_size, output_size, in_spatial, stride,
                             padding, n):
    """filter_size from output_size (reference conv2d_transpose contract):
    k = out - (in - 1)*stride + 2*pad."""
    if filter_size is not None:
        return filter_size
    if output_size is None:
        raise ValueError("either filter_size or output_size is required")
    outs = [output_size] * n if isinstance(output_size, int) \
        else list(output_size)
    strides = [stride] * n if isinstance(stride, int) else list(stride)
    pads = [padding] * n if isinstance(padding, int) else list(padding)
    return [outs[i] - (in_spatial[i] - 1) * strides[i] + 2 * pads[i]
            for i in range(n)]


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,  # noqa: A002
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    spatial = input.shape[2:] if data_format == "NCHW" else input.shape[1:-1]
    filter_size = _derive_transpose_filter(filter_size, output_size,
                                           spatial, stride, padding, 2)
    layer = _nn.Conv2DTranspose(in_ch, num_filters, filter_size,
                                stride=stride, padding=padding,
                                dilation=dilation, groups=groups,
                                weight_attr=param_attr, bias_attr=bias_attr,
                                data_format=data_format)
    out = layer(input, output_size=output_size)
    return getattr(_nn.functional, act)(out) if act else out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCDHW"):
    in_ch = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    layer = _nn.Conv3D(in_ch, num_filters, filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    out = layer(input)
    return getattr(_nn.functional, act)(out) if act else out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,  # noqa: A002
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    in_ch = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    spatial = input.shape[2:] if data_format == "NCDHW" \
        else input.shape[1:-1]
    filter_size = _derive_transpose_filter(filter_size, output_size,
                                           spatial, stride, padding, 3)
    layer = _nn.Conv3DTranspose(in_ch, num_filters, filter_size,
                                stride=stride, padding=padding,
                                dilation=dilation, groups=groups,
                                weight_attr=param_attr, bias_attr=bias_attr,
                                data_format=data_format)
    out = layer(input, output_size=output_size)
    return getattr(_nn.functional, act)(out) if act else out


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    layer = _nn.Bilinear(x.shape[-1], y.shape[-1], size,
                         weight_attr=param_attr, bias_attr=bias_attr)
    out = layer(x, y)
    return getattr(_nn.functional, act)(out) if act else out


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, weight_attr=None, bias_attr=None,
                  name=None):
    from ..vision.ops import DeformConv2D

    layer = DeformConv2D(x.shape[1], num_filters, filter_size,
                         stride=stride, padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups, groups=groups,
                         weight_attr=weight_attr, bias_attr=bias_attr)
    return layer(x, offset, mask)


def row_conv(input, future_context_size, param_attr=None, act=None):  # noqa: A002
    """Lookahead row convolution (Deep Speech 2): y[t] = sum_{i<=k} w_i *
    x[t+i], implemented as a depthwise temporal conv."""
    from ..core.autograd import apply as _apply
    from ..nn.layer.layers import create_parameter
    import jax.numpy as jnp

    k = future_context_size
    d = input.shape[-1]
    w = create_parameter([k + 1, d], "float32", attr=param_attr,
                         default_initializer=_nn.initializer.Constant(0.1))

    def _f(xv, wv):
        pads = [(0, 0)] * xv.ndim
        pads[-2] = (0, k)
        xp = jnp.pad(xv, pads)
        t = xv.shape[-2]
        out = 0.0
        for i in range(k + 1):
            out = out + xp[..., i:i + t, :] * wv[i]
        return out

    _f.__name__ = "row_conv"
    out = _apply(_f, input, w)
    return getattr(_nn.functional, act)(out) if act else out


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-Python op inside the program (reference static/nn py_func),
    bridged with jax.pure_callback via utils.custom_op."""
    import numpy as _np

    from ..utils.custom_op import register_custom_op

    outs = out if isinstance(out, (list, tuple)) else (out,)
    shapes = tuple((tuple(o.shape), _np.dtype(str(o.numpy().dtype)))
                   for o in outs)

    op = register_custom_op(
        getattr(func, "__name__", "py_func"), func,
        infer_shape=lambda *a: shapes if len(shapes) > 1 else shapes[0],
        backward=backward_func)
    xs = x if isinstance(x, (list, tuple)) else (x,)
    return op(*xs)


# legacy sequence / misc ops (see static/sequence_ops.py for the padded-
# dense + lengths design; reference fluid/layers/sequence_lod.py)
from .sequence_ops import (  # noqa: E402,F401
    crf_decoding, multi_box_head, nce, sequence_concat, sequence_conv,
    sequence_enumerate, sequence_expand, sequence_expand_as,
    sequence_first_step, sequence_last_step, sequence_pad, sequence_pool,
    sequence_reshape, sequence_reverse, sequence_scatter, sequence_slice,
    prior_box, sequence_softmax, sequence_unpad, sparse_embedding,
)
from .sequence_ops import __all__ as _seq_all

__all__ = list(__all__) + list(_seq_all)
