"""Legacy sequence ops (reference: fluid/layers/sequence_lod.py).

The reference operates on LoDTensors (ragged sequences carried as a flat
tensor + level-of-detail offsets). This runtime has no LoD: the TPU-native
carrier for ragged batches is a PADDED dense tensor [batch, max_len, ...]
plus an explicit `lengths` vector — the layout XLA can tile (static
shapes; masks instead of offsets). Every op below takes that pair; with
lengths=None the batch is treated as fully dense. sequence_pad/unpad
convert between the two worlds exactly like the reference pair does.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import apply

__all__ = ["sequence_conv", "sequence_pool", "sequence_concat",
           "sequence_first_step", "sequence_last_step", "sequence_slice",
           "sequence_expand", "sequence_expand_as", "sequence_pad",
           "sequence_unpad", "sequence_reshape", "sequence_scatter",
           "sequence_enumerate", "sequence_softmax", "sequence_reverse",
           "crf_decoding", "nce", "sparse_embedding", "multi_box_head",
           "prior_box"]


def _len_mask(lengths, max_len):
    return jnp.arange(max_len)[None, :] < lengths[:, None]


def _unwrap(x):
    # shared unwrapping lives in core.autograd._raw; asarray covers plain
    # numpy/python inputs
    from ..core.autograd import _raw

    return jnp.asarray(_raw(x))


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """List-of-rows + lengths world entry point: here x is already
    [batch, time, ...]; returns (x padded to maxlen, lengths). Reference
    sequence_lod.py::sequence_pad emits the same (Out, Length) pair."""
    def f(v, pv):
        t = v.shape[1]
        tgt = t if maxlen is None else maxlen
        if tgt < t:
            raise ValueError(
                f"sequence_pad: maxlen ({tgt}) must be >= the input time "
                f"dimension ({t}) — the reference errors here too")
        if tgt > t:
            pad = [(0, 0), (0, tgt - t)] + [(0, 0)] * (v.ndim - 2)
            v = jnp.pad(v, pad, constant_values=pv)
        lengths = jnp.full((v.shape[0],), t, jnp.int64)
        return v, lengths

    return apply(f, x, pad_value)


def sequence_unpad(x, length, name=None):
    """[batch, max_len, ...] + lengths -> flat [sum(len), ...] (the
    reference's LoD-flat layout; data-dependent shape => eager)."""
    def f(v, ln):
        rows = [v[i, :int(l)] for i, l in enumerate(ln)]
        return jnp.concatenate(rows, axis=0)

    return apply(f, x, length)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0,
                  lengths=None, name=None):
    pool_type = pool_type.lower()

    def f(v, ln):
        t = v.shape[1]
        ln_ = ln if ln is not None else jnp.full((v.shape[0],), t)
        mask = _len_mask(ln_, t)
        mshape = mask.shape + (1,) * (v.ndim - 2)
        m = mask.reshape(mshape)
        n = jnp.maximum(ln_, 1).reshape((-1,) + (1,) * (v.ndim - 2))
        empty = (ln_ == 0).reshape((-1,) + (1,) * (v.ndim - 2))

        def _fill(out):
            # zero-length sequences pool to pad_value (reference contract)
            return jnp.where(empty, jnp.asarray(pad_value, out.dtype), out)

        if pool_type == "sum":
            return _fill(jnp.where(m, v, 0).sum(1))
        if pool_type in ("average", "avg"):
            return _fill(jnp.where(m, v, 0).sum(1) / n)
        if pool_type == "sqrt":
            return _fill(jnp.where(m, v, 0).sum(1) / jnp.sqrt(
                n.astype(jnp.float32)))
        if pool_type == "max":
            return _fill(jnp.where(m, v, -jnp.inf).max(1))
        if pool_type == "first":
            return _fill(v[:, 0])
        if pool_type == "last":
            idx = jnp.maximum(ln_ - 1, 0)
            return _fill(jnp.take_along_axis(
                v, idx.reshape((-1, 1) + (1,) * (v.ndim - 2)), 1)[:, 0])
        raise ValueError(f"unknown pool_type {pool_type!r}")

    return apply(f, input, lengths)


def sequence_first_step(input, lengths=None):
    return sequence_pool(input, "first", lengths=lengths)


def sequence_last_step(input, lengths=None):
    return sequence_pool(input, "last", lengths=lengths)


def sequence_softmax(input, use_cudnn=False, name=None, lengths=None):
    def f(v, ln):
        t = v.shape[1]
        ln_ = ln if ln is not None else jnp.full((v.shape[0],), t)
        mask = _len_mask(ln_, t).reshape(
            (v.shape[0], t) + (1,) * (v.ndim - 2))
        logits = jnp.where(mask, v, -jnp.inf)
        return jnp.where(mask, jax.nn.softmax(logits, axis=1), 0.0)

    return apply(f, input, lengths)


def sequence_reverse(x, name=None, lengths=None):
    def f(v, ln):
        t = v.shape[1]
        ln_ = ln if ln is not None else jnp.full((v.shape[0],), t)
        idx = ln_[:, None] - 1 - jnp.arange(t)[None, :]
        idx = jnp.where(idx >= 0, idx, jnp.arange(t)[None, :])
        return jnp.take_along_axis(
            v, idx.reshape(idx.shape + (1,) * (v.ndim - 2)), 1)

    return apply(f, x, lengths)


def sequence_concat(input, name=None):
    """Concatenate along time (reference concats per-sequence LoD rows;
    the padded equivalent concatenates the time axis)."""
    def f(*vs):
        return jnp.concatenate(vs, axis=1)

    return apply(f, *input)


def sequence_slice(input, offset, length, name=None):
    def f(v, off, ln):
        t = v.shape[1]
        idx = off.reshape(-1, 1) + jnp.arange(t)[None, :]
        idx = jnp.clip(idx, 0, t - 1)
        g = jnp.take_along_axis(
            v, idx.reshape(idx.shape + (1,) * (v.ndim - 2)), 1)
        mask = jnp.arange(t)[None, :] < ln.reshape(-1, 1)
        return jnp.where(mask.reshape(mask.shape + (1,) * (v.ndim - 2)),
                         g, 0)

    return apply(f, input, offset, length)


def sequence_expand(x, y, ref_level=-1, name=None, repeats=None):
    """Repeat each batch row per `repeats` (reference expands rows per
    y's LoD; padded world: explicit repeat counts; data-dependent shape
    => eager)."""
    def f(v, rep):
        return jnp.repeat(v, rep, axis=0, total_repeat_length=int(
            jnp.sum(rep)))

    if repeats is None:
        repeats = y
    return apply(f, x, repeats)


def sequence_expand_as(x, y, name=None):
    def f(v, w):
        reps = w.shape[0] // v.shape[0]
        return jnp.repeat(v, reps, axis=0)

    return apply(f, x, y)


def sequence_reshape(input, new_dim):
    def f(v):
        return v.reshape(v.shape[0], -1, new_dim)

    return apply(f, input)


def sequence_scatter(input, index, updates, name=None):
    def f(v, idx, upd):
        return v.at[jnp.arange(v.shape[0])[:, None], idx].add(upd)

    return apply(f, input, index, updates)


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    def f(v):
        t = v.shape[1]
        base = jnp.arange(t)[:, None] + jnp.arange(win_size)[None, :]
        gathered = jnp.where(base < t, v[:, jnp.clip(base, 0, t - 1)],
                             pad_value)
        return gathered

    return apply(f, input)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Sliding-window 1-D conv over time (reference sequence_conv):
    implemented as a Conv1D over the padded layout."""
    from .. import nn

    conv = nn.Conv1D(int(input.shape[-1]), num_filters, filter_size,
                     stride=filter_stride,
                     padding=(filter_size - 1) // 2 if padding else 0,
                     weight_attr=param_attr, bias_attr=bias_attr,
                     data_format="NLC")
    out = conv(input)
    if act == "relu":
        out = nn.functional.relu(out)
    elif act == "tanh":
        out = nn.functional.tanh(out)
    return out


def crf_decoding(input, param_attr=None, label=None, length=None,
                 transition=None):
    """Viterbi decode (reference crf_decoding over linear_chain_crf
    transitions). transition: [num_tags + 2, num_tags] or
    [num_tags, num_tags]; the +2 start/stop rows of the reference CRF are
    folded into the first/last emissions (same decoded path)."""
    from ..core.tensor import Tensor
    from ..text import viterbi_decode

    if transition is None:
        raise ValueError("crf_decoding needs the CRF `transition` tensor "
                         "(the reference reads it from param_attr's "
                         "learned variable)")
    t = _unwrap(transition)
    n_tags = int(input.shape[-1])
    emis = _unwrap(input)
    if t.shape[0] == n_tags + 2:
        start, stop, t = t[0], t[1], t[2:]
        emis = emis.at[:, 0, :].add(start)
        if length is not None:
            ln = _unwrap(length).astype(jnp.int64)
            last = jnp.clip(ln - 1, 0, emis.shape[1] - 1)
            emis = emis.at[jnp.arange(emis.shape[0]), last, :].add(stop)
        else:
            emis = emis.at[:, -1, :].add(stop)
    _, path = viterbi_decode(Tensor(emis), t, lengths=length,
                             include_bos_eos_tag=False)
    return path


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, param_attr=None, dtype="float32"):
    """Reference sparse_embedding stores rows on parameter servers (PS
    waiver — SURVEY §2); the mesh-native equivalent is a dense (or
    vocab-sharded, via mp_layers.VocabParallelEmbedding) embedding."""
    from .. import nn

    emb = nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                       weight_attr=param_attr)
    return emb(input)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False,
        weight=None, bias=None):
    """Noise-contrastive estimation loss (reference nce op): logistic
    discrimination of the true class against `num_neg_samples` negatives.
    sampler: 'uniform' | 'log_uniform' | 'custom_dist' (with custom_dist
    = per-class probabilities); `seed` gives reproducible negatives. Pass
    `weight` [num_classes, dim] (and optional `bias`) explicitly — the
    functional world has no hidden ParamAttr store."""
    if weight is None:
        raise ValueError("nce needs the class `weight` matrix (the "
                         "reference creates it from param_attr)")
    if sampler not in ("uniform", "log_uniform", "custom_dist"):
        raise ValueError(f"unknown sampler {sampler!r}")
    if sampler == "custom_dist" and custom_dist is None:
        raise ValueError("sampler='custom_dist' needs custom_dist")

    def f(h, y, w, b, sw, key):
        n, d = h.shape
        if sampler == "uniform":
            neg = jax.random.randint(key, (n, num_neg_samples), 0,
                                     num_total_classes)
        elif sampler == "log_uniform":
            # P(k) ∝ log(k+2)-log(k+1) — the Zipfian sampler
            u = jax.random.uniform(key, (n, num_neg_samples))
            neg = (jnp.exp(u * jnp.log(num_total_classes + 1.0))
                   - 1.0).astype(jnp.int32)
            neg = jnp.clip(neg, 0, num_total_classes - 1)
        else:
            logits = jnp.log(jnp.asarray(custom_dist) + 1e-20)
            neg = jax.random.categorical(
                key, logits[None, :], shape=(n, num_neg_samples))
        pos_w = w[y.reshape(-1)]                        # [n, d]
        pos_logit = (h * pos_w).sum(-1)
        if b is not None:
            pos_logit = pos_logit + b[y.reshape(-1)]
        neg_w = w[neg]                                  # [n, k, d]
        neg_logit = jnp.einsum("nd,nkd->nk", h, neg_w)
        if b is not None:
            neg_logit = neg_logit + b[neg]
        loss = -jax.nn.log_sigmoid(pos_logit) \
            - jax.nn.log_sigmoid(-neg_logit).sum(-1)
        if sw is not None:
            loss = loss * sw.reshape(-1)
        return loss.reshape(-1, 1)

    from ..framework import random as rnd

    if seed:
        # seeded STREAM: fresh negatives each call, reproducible across
        # runs (seed=0 = "use the global stream", the reference op's
        # convention for its default)
        counter = _nce_counters.get(seed, 0)
        _nce_counters[seed] = counter + 1
        key = jax.random.fold_in(jax.random.PRNGKey(seed), counter)
    else:
        key = rnd.next_key()
    return apply(f, input, label, weight, bias, sample_weight, key)


_nce_counters = {}


def _prior_whs(min_sizes, max_sizes, aspect_ratios, flip, iw, ih):
    """(w, h) of every prior a cell generates — the SINGLE source of truth
    for the prior count, shared by prior_box and multi_box_head."""
    ratios = list(aspect_ratios)
    if flip:
        ratios = ratios + [1.0 / r for r in ratios if r != 1.0]
    whs = []
    for ms in min_sizes:
        for r in ratios:
            whs.append((ms * (r ** 0.5) / iw, ms / (r ** 0.5) / ih))
    if max_sizes:
        for ms, mx in zip(min_sizes, max_sizes):
            s = (ms * mx) ** 0.5
            whs.append((s / iw, s / ih))
    return whs


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    """SSD prior boxes (reference fluid/layers/detection.py::prior_box):
    per feature-map cell, one box per (min_size x aspect ratio) plus one
    per (min,max) geometric mean, corner coords normalized by image size."""
    def f(fmap, img):
        fh, fw = fmap.shape[2], fmap.shape[3]
        ih, iw = img.shape[2], img.shape[3]
        step_w = steps[0] or iw / fw
        step_h = steps[1] or ih / fh
        cx = (jnp.arange(fw) + offset) * step_w / iw   # [fw]
        cy = (jnp.arange(fh) + offset) * step_h / ih   # [fh]
        wh = jnp.asarray(_prior_whs(min_sizes, max_sizes, aspect_ratios,
                                    flip, iw, ih))     # [P, 2]
        cxg, cyg = jnp.meshgrid(cx, cy)                # [fh, fw]
        centers = jnp.stack([cxg, cyg], -1)[:, :, None, :]  # [fh,fw,1,2]
        half = wh[None, None, :, :] / 2
        boxes = jnp.concatenate([centers - half, centers + half], -1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance), boxes.shape)
        return boxes, var

    return apply(f, input, image)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD prior-box head (reference multi_box_head): conv loc/conf
    predictions + prior boxes for each feature map. Per-map min/max sizes
    derive from min_ratio..max_ratio when not given (reference formula);
    the conv channel counts come from the SAME _prior_whs the boxes do,
    so locs and boxes always align."""
    from .. import nn

    n_maps = len(inputs)
    if min_sizes is None:
        # reference: interpolate ratios across feature maps; the first map
        # uses base_size * 10% / 20%
        assert min_ratio is not None and max_ratio is not None, \
            "give min_sizes/max_sizes or min_ratio/max_ratio"
        min_sizes, max_sizes = [], []
        if n_maps > 2:
            step = int((max_ratio - min_ratio) / (n_maps - 2))
            for ratio in range(min_ratio, max_ratio + 1, step):
                min_sizes.append(base_size * ratio / 100.0)
                max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes

    def _per_map(lst, i):
        if lst is None:
            return None
        e = lst[i] if isinstance(lst, (list, tuple)) and \
            i < len(lst) else lst[-1] if isinstance(lst, (list, tuple)) \
            else lst
        return e if isinstance(e, (list, tuple)) else [e]

    locs, confs, boxes, variances = [], [], [], []
    ih, iw = int(image.shape[2]), int(image.shape[3])
    from .. import tensor as T

    for i, x in enumerate(inputs):
        c = int(x.shape[1])
        ms = _per_map(min_sizes, i)
        mx = _per_map(max_sizes, i)
        ar = _per_map(aspect_ratios, i) or [1.0]
        n_priors = len(_prior_whs(ms, mx, ar, flip, iw, ih))
        # per-map step: explicit steps list > step_w/step_h > auto
        st = _per_map(steps, i) if steps else None
        sw = st[0] if st else (step_w or 0.0)
        sh = st[-1] if st else (step_h or 0.0)
        loc = nn.Conv2D(c, n_priors * 4, kernel_size, padding=pad,
                        stride=stride)(x)
        conf = nn.Conv2D(c, n_priors * num_classes, kernel_size,
                         padding=pad, stride=stride)(x)
        n = int(loc.shape[0])
        # NCHW conv maps -> [N, priors_of_map, 4|C] (reference layout)
        locs.append(T.reshape(T.transpose(loc, [0, 2, 3, 1]), [n, -1, 4]))
        confs.append(T.reshape(T.transpose(conf, [0, 2, 3, 1]),
                               [n, -1, num_classes]))
        box, var = prior_box(x, image, min_sizes=ms, max_sizes=mx,
                             aspect_ratios=ar, variance=list(variance),
                             flip=flip, clip=clip, steps=(sw, sh),
                             offset=offset)
        boxes.append(box.reshape([-1, 4]))
        variances.append(var.reshape([-1, 4]))
    return (T.concat(locs, axis=1), T.concat(confs, axis=1),
            T.concat(boxes, axis=0), T.concat(variances, axis=0))
