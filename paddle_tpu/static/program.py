"""Static graph: Program IR + program_guard + data
(reference: python/paddle/fluid/framework.py ProgramDesc/Block/Operator).

TPU-native design (SURVEY §3): building a Program = concrete tracing. While
static mode is on, every op that flows through the eager dispatcher executes
on small dummy values (dynamic dims pinned to 1) AND appends an op record —
(pure fn, input refs, output refs) — to the current Program. `Executor.run`
replays the record as ONE jit-compiled XLA function of (params, feeds), so
the whole graph compiles into a single device program: strictly better than
the reference's op-by-op kernel launches.

Dispatch-cache interplay: while a recorder is installed, apply() takes the
recorder branch BEFORE the jit-cached dispatch (core/dispatch.py), so
build-time ops run plain-eager on the dummy values — the recorded `op.fn`
is replayed inside the Executor's single whole-graph jit, where per-op
cache entries (keyed on throwaway dummy shapes) would be pure overhead.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd as _ag
from ..core import dtype as dtypes
from ..core.tensor import Tensor

__all__ = ["Program", "Variable", "program_guard", "data",
           "default_main_program", "default_startup_program", "name_scope",
           "InputSpec"]


class InputSpec:
    """paddle.static.InputSpec (reference: python/paddle/static/input.py)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype.name, name or tensor.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


class Variable(Tensor):
    """Symbolic placeholder: carries a dummy value (dynamic dims -> 1) for
    concrete tracing, plus the declared shape with -1s."""

    __slots__ = ("declared_shape", "is_data")

    def __init__(self, value, declared_shape, name):
        super().__init__(value, stop_gradient=True, name=name)
        self.declared_shape = list(declared_shape)
        self.is_data = True

    @property
    def shape(self):
        return list(self.declared_shape)


class OpRecord:
    __slots__ = ("fn", "in_refs", "treedef", "out_ids", "name")

    def __init__(self, fn, in_refs, treedef, out_ids):
        self.fn = fn
        self.in_refs = in_refs   # list of ("var", id) | ("const", value)
        self.treedef = treedef
        self.out_ids = out_ids
        self.name = getattr(fn, "__name__", "op")


class Program:
    """Recorded op list + var registry (reference ProgramDesc)."""

    def __init__(self):
        self.ops = []
        self.feed_vars = {}      # name -> Variable
        self.param_ids = {}      # id(param) -> Parameter
        self.const_ids = {}      # id(tensor) -> raw value (captured consts)
        self.minimize_records = []  # (optimizer, loss_tensor)
        self._rand_ids = set()
        self.random_seed = None

    # recording --------------------------------------------------------
    def record_op(self, fn, flat, treedef, out_tree):
        in_refs = []
        for a in flat:
            if isinstance(a, Tensor):
                in_refs.append(("var", id(a)))
                self._note_input(a)
            else:
                in_refs.append(("const", a))
        out_leaves = jax.tree_util.tree_leaves(
            out_tree, is_leaf=lambda x: isinstance(x, Tensor))
        out_ids = [id(o) for o in out_leaves]
        self.ops.append(OpRecord(fn, in_refs, treedef, out_ids))

    def _note_input(self, t):
        from ..nn.layer.layers import Parameter

        if isinstance(t, Variable):
            return
        if isinstance(t, Parameter):
            self.param_ids[id(t)] = t
            return
        produced = any(id(t) in op.out_ids for op in self.ops)
        if not produced:
            # leaf constant created during build (e.g. rng draw, to_tensor)
            self.const_ids[id(t)] = t._value

    def add_feed(self, var):
        self.feed_vars[var.name] = var

    # introspection ----------------------------------------------------
    def num_ops(self):
        return len(self.ops)

    def all_parameters(self):
        return list(self.param_ids.values())

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy

        p = Program()
        p.ops = list(self.ops)
        p.feed_vars = dict(self.feed_vars)
        p.param_ids = dict(self.param_ids)
        p.const_ids = dict(self.const_ids)
        if not for_test:
            p.minimize_records = list(self.minimize_records)
        return p

    def __repr__(self):
        lines = [f"Program(ops={len(self.ops)}, feeds={list(self.feed_vars)}, "
                 f"params={len(self.param_ids)})"]
        for op in self.ops:
            lines.append(f"  {op.name} -> {len(op.out_ids)} out")
        return "\n".join(lines)

    # replay -----------------------------------------------------------
    def build_fn(self, fetch_ids, train=False):
        """Pure function (param_vals dict, feed_vals dict) ->
        (fetch values, new_param_vals, new_opt_states)."""
        ops = self.ops
        const_ids = self.const_ids
        pid_names = {pid: p.name for pid, p in self.param_ids.items()}
        feed_name_by_id = {id(v): name for name, v in self.feed_vars.items()}
        minimizes = self.minimize_records if train else []

        def forward_env(param_vals, feed_vals):
            env = {}
            for pid, name in pid_names.items():
                env[pid] = param_vals[name]
            for name, v in self.feed_vars.items():
                env[id(v)] = feed_vals[name]
            for cid, val in const_ids.items():
                env[cid] = val
            for op in ops:
                flat = []
                for kind, ref in op.in_refs:
                    if kind == "var":
                        if ref not in env:
                            raise RuntimeError(
                                f"static replay: missing input for op "
                                f"{op.name}; was a tensor created outside "
                                "the program used inside it?")
                        flat.append(env[ref])
                    else:
                        flat.append(ref)
                args, kwargs = jax.tree_util.tree_unflatten(op.treedef, flat)
                out = op.fn(*args, **kwargs)
                leaves = jax.tree_util.tree_leaves(out)
                for oid, leaf in zip(op.out_ids, leaves):
                    env[oid] = leaf
            return env

        if not minimizes:
            def run(param_vals, feed_vals):
                env = forward_env(param_vals, feed_vals)
                return [env[i] for i in fetch_ids], param_vals, None
            return run

        optimizer, loss_t = minimizes[0]

        def run(param_vals, feed_vals, opt_states, lr):
            def loss_of(pv):
                env = forward_env(pv, feed_vals)
                return env[id(loss_t)].astype(jnp.float32), env
            (loss, env), grads = jax.value_and_grad(  # tracelint: ok[suspend-audit] forward_env replays raw op.fn
                loss_of, has_aux=True)(param_vals)
            meta = optimizer.param_meta(
                {name: p for pid, p in self.param_ids.items()
                 for name in [p.name]})
            new_params, new_states = optimizer.functional_update(
                param_vals, grads, opt_states, lr, meta=meta,
                clip=getattr(optimizer, "_grad_clip", None))
            fetches = [env[i] if i != id(loss_t) else loss for i in fetch_ids]
            return fetches, new_params, new_states
        return run


_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


class _Recorder:
    def __init__(self, program):
        self.program = program

    def record_op(self, fn, flat, treedef, out_tree):
        self.program.record_op(fn, flat, treedef, out_tree)


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    prev_main, prev_startup = _main_program, _startup_program
    prev_rec = _ag._static_recorder
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    _ag._static_recorder = _Recorder(main_program)
    try:
        yield
    finally:
        _main_program, _startup_program = prev_main, prev_startup
        _ag._static_recorder = prev_rec


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def data(name, shape, dtype=None, lod_level=0):
    """paddle.static.data: declare a feed slot. Dynamic dims (-1/None) are
    pinned to 1 for build-time concrete tracing; the Executor re-specializes
    per actual feed shape (jit cache keyed on shapes)."""
    shape = [s if s is not None else -1 for s in shape]
    dummy_shape = [1 if s == -1 else int(s) for s in shape]
    jd = dtypes.to_jax_dtype(dtype or dtypes.get_default_dtype())
    v = Variable(jnp.zeros(dummy_shape, jd), shape, name)
    _main_program.add_feed(v)
    return v
