"""paddle.static.amp — mixed precision for the static-graph path.

Reference: python/paddle/static/amp (re-exports
fluid/contrib/mixed_precision: decorate, AutoMixedPrecisionLists,
fp16_guard, cast_model_to_fp16/parameters_to_fp16, bf16 submodule).

TPU-native: static programs trace through the same eager ops as dygraph,
so the dygraph AMP machinery (auto_cast policy + decorate) IS the static
policy; fp16 requests map to bf16 on TPU. cast_model_to_fp16 /
cast_parameters_to_fp16 operate on a Program's parameters directly.
"""
from __future__ import annotations

import types

from ..amp import GradScaler, amp_guard, auto_cast, decorate  # noqa: F401

__all__ = ["decorate", "CustomOpLists", "AutoMixedPrecisionLists",
           "fp16_guard", "cast_model_to_fp16", "cast_parameters_to_fp16",
           "bf16", "auto_cast", "amp_guard", "GradScaler"]


class AutoMixedPrecisionLists:
    """White/black op lists (reference fp16_lists.py)."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = set(custom_white_list or [])
        self.black_list = set(custom_black_list or [])
        self.black_varnames = set(custom_black_varnames or [])


CustomOpLists = AutoMixedPrecisionLists


def fp16_guard():
    """Context marking a region for fp16 (-> bf16 on TPU) execution."""
    return auto_cast(enable=True, level="O2")


def _cast_params(program, dtype):
    import jax.numpy as jnp

    n = 0
    for p in getattr(program, "param_ids", {}).values():
        if jnp.issubdtype(p._value.dtype, jnp.floating):
            p._value = p._value.astype(dtype)
            n += 1
    return n


def cast_model_to_fp16(program, amp_lists=None, use_fp16_guard=True):
    """Cast a Program's float parameters to bf16 (TPU's fp16-class type)."""
    import jax.numpy as jnp

    _cast_params(program, jnp.bfloat16)
    return program


def cast_parameters_to_fp16(place, program, scope=None, to_fp16_var_names=None):
    import jax.numpy as jnp

    _cast_params(program, jnp.bfloat16)


# bf16 submodule (reference static/amp/bf16): on TPU bf16 IS the amp dtype
bf16 = types.ModuleType(__name__ + ".bf16")
bf16.auto_cast = auto_cast
bf16.decorate_bf16 = decorate
bf16.AutoMixedPrecisionListsBF16 = AutoMixedPrecisionLists
bf16.cast_model_to_bf16 = cast_model_to_fp16
bf16.cast_parameters_to_bf16 = cast_parameters_to_fp16
import sys as _sys

_sys.modules[bf16.__name__] = bf16
