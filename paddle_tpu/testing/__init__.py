"""paddle_tpu.testing — on-device validation utilities (tpu_checks)."""
