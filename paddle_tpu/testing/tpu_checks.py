"""On-device correctness checks for the perf-path kernels.

The reference's fused GPU kernels are proven on their hardware by unit
tests (e.g. /root/reference/paddle/phi/kernels/gpu/cross_entropy_kernel.cu
exercised through the softmax_with_cross_entropy op tests); this module is
the TPU analogue for the kernels this framework's perf story rests on:
Pallas flash attention (fwd + bwd), ring attention, the blockwise fused
LM-head CE, and int8 MXU matmul. CPU/interpret-mode tests pin the math;
these checks pin the LOWERED kernels on the live backend (non-interpret
Mosaic), where tiling, VMEM layout, and MXU precision are real.

Two consumers, one implementation:
  * bench.py's `tpu_correctness` config runs it while the bench client
    holds the chip grant (results land in the bench JSON);
  * tests/test_tpu_correctness.py wraps it as a @pytest.mark.tpu suite
    that auto-skips off-TPU.

The oracle is host numpy float64 — independent of the device under test.
f32 tolerances absorb the MXU's f32 matmul path (bf16-multiplier passes);
kernel-vs-kernel comparisons (block tilings) are near-exact.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ["run_tpu_checks"]


def _np_attention(q, k, v, causal=False, kv_mask=None):
    """float64 host oracle: softmax(q.k^T/sqrt(d) [+masks]).v"""
    q, k, v = (np.asarray(x, np.float64) for x in (q, k, v))
    s = np.einsum("bqd,bkd->bqk", q, k) / math.sqrt(q.shape[-1])
    if causal:
        ql, kl = s.shape[-2], s.shape[-1]
        s = np.where(np.tril(np.ones((ql, kl), bool)), s, -1e30)
    if kv_mask is not None:  # [b, kl] 1=keep
        s = np.where(np.asarray(kv_mask, bool)[:, None, :], s, -1e30)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(axis=-1, keepdims=True)
    return np.einsum("bqk,bkd->bqd", p, v)


def _max_err(a, b):
    return float(np.max(np.abs(np.asarray(a, np.float64)
                               - np.asarray(b, np.float64))))


def run_tpu_checks(seq=256, dim=64, bh=8, vocab=8192, hidden=256, n=512):
    """Execute every check on the CURRENT jax backend; returns a flat
    dict of `tpu_check_*` floats plus pass booleans and an overall
    `tpu_checks_passed`. Never raises: a check that errors records the
    exception and fails the overall flag (one broken kernel must not
    hide the other kernels' evidence)."""
    import jax
    import jax.numpy as jnp

    from ..ops.blockwise_ce import blockwise_softmax_ce
    from ..ops.pallas.flash_attention import flash_attention_raw

    out = {"tpu_checks_backend": jax.default_backend()}
    passed = []

    def check(name, fn, tol=None):
        try:
            err = fn()
            out[f"tpu_check_{name}_err"] = err
            ok = (err <= tol) if tol is not None else bool(err == 0.0)
            out[f"tpu_check_{name}_ok"] = ok
            passed.append(ok)
        except Exception as e:  # noqa: BLE001 — record, keep checking
            out[f"tpu_check_{name}_error"] = f"{type(e).__name__}: {e}"[:200]
            passed.append(False)

    rng = np.random.RandomState(0)
    qn, kn, vn = (rng.randn(bh, seq, dim).astype(np.float32)
                  for _ in range(3))
    q, k, v = (jnp.asarray(x) for x in (qn, kn, vn))
    oracle_causal = _np_attention(qn, kn, vn, causal=True)
    oracle_plain = _np_attention(qn, kn, vn, causal=False)

    # --- flash attention forward, f32 and bf16, causal and plain -------
    # f32 tol: MXU f32 matmuls run as bf16-multiplier passes (~1e-3 rel);
    # unit-variance inputs keep outputs O(1) so max-abs tracks rel err.
    check("flash_f32_causal",
          lambda: _max_err(jax.jit(flash_attention_raw,  # tracelint: ok[suspend-audit] raw flash/XLA kernels
                                   static_argnums=3)(q, k, v, True),
                           oracle_causal), tol=5e-3)
    check("flash_f32_plain",
          lambda: _max_err(jax.jit(flash_attention_raw,  # tracelint: ok[suspend-audit] raw flash/XLA kernels
                                   static_argnums=3)(q, k, v, False),
                           oracle_plain), tol=5e-3)
    check("flash_bf16_causal",
          lambda: _max_err(
              jax.jit(flash_attention_raw, static_argnums=3)(  # tracelint: ok[suspend-audit] raw flash/XLA kernels
                  q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
                  v.astype(jnp.bfloat16), True).astype(jnp.float32),
              oracle_causal), tol=6e-2)

    # --- flash with key-padding mask ----------------------------------
    kvm_n = (rng.rand(bh, seq) > 0.25).astype(np.float32)
    kvm_n[:, 0] = 1.0  # no fully-masked rows
    check("flash_masked",
          lambda: _max_err(
              flash_attention_raw(q, k, v, False,
                                  kv_mask=jnp.asarray(kvm_n)),
              _np_attention(qn, kn, vn, causal=False, kv_mask=kvm_n)),
          tol=5e-3)

    # --- flash backward: custom-vjp kernel vs XLA autodiff -------------
    # grads of mean(out^2) through the Pallas split dq/dkv backward vs
    # jax.grad through a plain XLA attention on the same device — the
    # kernel-vs-XLA comparison, sharing the hardware's matmul precision
    # so the tolerance isolates the kernel math itself.
    def _xla_attn_dev(qq, kk, vv, causal):
        s = jnp.einsum("bqd,bkd->bqk", qq, kk) / math.sqrt(qq.shape[-1])
        if causal:
            ql, kl = s.shape[-2], s.shape[-1]
            s = jnp.where(jnp.tril(jnp.ones((ql, kl), bool)), s, -1e30)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(qq.dtype)
        return jnp.einsum("bqk,bkd->bqd", p, vv)

    def _grad_err():
        def flash_loss(qq, kk, vv):
            return (flash_attention_raw(qq, kk, vv, True) ** 2).mean()

        def xla_loss(qq, kk, vv):
            return (_xla_attn_dev(qq, kk, vv, True) ** 2).mean()

        gf = jax.jit(jax.grad(flash_loss, argnums=(0, 1, 2)))(q, k, v)  # tracelint: ok[suspend-audit] raw flash/XLA kernels
        gx = jax.jit(jax.grad(xla_loss, argnums=(0, 1, 2)))(q, k, v)  # tracelint: ok[suspend-audit] raw flash/XLA kernels
        return max(_max_err(a, b) for a, b in zip(gf, gx))

    check("flash_bwd_vs_xla", _grad_err, tol=5e-3)

    # --- non-default block tilings: kernel vs kernel, near-exact -------
    try:
        base = np.asarray(jax.jit(flash_attention_raw,  # tracelint: ok[suspend-audit] raw flash/XLA kernels
                                  static_argnums=3)(q, k, v, True))
    except Exception as e:  # noqa: BLE001 — later checks must still run
        out["tpu_check_flash_tiling_error"] = (
            f"{type(e).__name__}: {e}"[:200])
        passed.append(False)
        base = None
    if base is not None:
        for bq, bk in ((128, 256), (256, 128), (256, 256)):
            if seq % bq or seq % bk:
                continue
            check(f"flash_tiling_q{bq}_k{bk}",
                  lambda bq=bq, bk=bk: _max_err(
                      flash_attention_raw(q, k, v, True,
                                          block_q=bq, block_k=bk), base),
                  tol=2e-5)

    # --- ring attention over a 1-chip mesh vs host oracle --------------
    # single-chip: the ring has one hop, which still exercises the
    # shard_map + ppermute + scan lowering on real hardware (the full
    # multi-hop parity is pinned on the 8-device CPU mesh).
    def _ring_err():
        from jax.sharding import Mesh

        from ..distributed.sequence_parallel import ring_attention

        mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))
        o = ring_attention(jnp.asarray(qn[None]), jnp.asarray(kn[None]),
                           jnp.asarray(vn[None]), mesh=mesh, causal=True)
        return _max_err(np.asarray(o)[0], oracle_causal)

    check("ring_causal", _ring_err, tol=5e-3)

    # --- blockwise fused LM-head CE: value + grads vs naive-on-device --
    hn = (rng.randn(n, hidden) * 0.02).astype(np.float32)
    wn = (rng.randn(vocab, hidden) * 0.02).astype(np.float32)
    yn = rng.randint(0, vocab, n)
    h, w, y = jnp.asarray(hn), jnp.asarray(wn), jnp.asarray(yn)

    def _naive(hh, ww):
        logits = hh @ ww.T
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, y[:, None], axis=-1)[:, 0]
        return (logz - picked).mean()

    check("blockwise_ce_value",
          lambda: _max_err(blockwise_softmax_ce(h, w, y, block=2048),
                           _naive(h, w)), tol=1e-4)

    def _ce_grad_err():
        gf = jax.jit(jax.grad(  # tracelint: ok[suspend-audit] raw flash/XLA kernels
            lambda hh, ww: blockwise_softmax_ce(hh, ww, y, block=2048),
            argnums=(0, 1)))
        gn = jax.jit(jax.grad(_naive, argnums=(0, 1)))  # tracelint: ok[suspend-audit] raw flash/XLA kernels
        return max(_max_err(a, b) for a, b in zip(gf(h, w), gn(h, w)))

    check("blockwise_ce_grad", _ce_grad_err, tol=1e-4)

    # --- int8 MXU matmul: bit-exact vs host int32 ----------------------
    a8 = rng.randint(-127, 127, (256, 256), dtype=np.int8)
    b8 = rng.randint(-127, 127, (256, 256), dtype=np.int8)
    check("int8_matmul_exact",
          lambda: float(np.max(np.abs(
              np.asarray(jax.lax.dot_general(
                  jnp.asarray(a8), jnp.asarray(b8),
                  (((1,), (0,)), ((), ())),
                  preferred_element_type=jnp.int32))
              - a8.astype(np.int32) @ b8.astype(np.int32)))))

    out["tpu_checks_passed"] = bool(passed) and all(passed)
    out["tpu_checks_total"] = len(passed)
    out["tpu_checks_failed"] = int(sum(1 for p in passed if not p))
    return out
