"""Fault-injection helpers for tests (and chaos drills).

Thin test-facing façade over runtime/resilience.py's FaultInjector: the
injector itself lives in the runtime (production chaos testing drives
it via ``PADDLE_TPU_FAULT_INJECT`` too); this module adds the bits only
tests want — env-spec rendering for child processes and checkpoint-
shard corruption targeting.
"""
from __future__ import annotations

import glob
import os

from ..runtime.resilience import (  # noqa: F401 — re-exported test surface
    FaultInjector, InjectedFault, corrupt_file, fault_events, fault_log,
    fault_point, record_fault, reset_fault_events,
)

__all__ = ["FaultInjector", "InjectedFault", "fault_point", "corrupt_file",
           "fault_events", "fault_log", "record_fault", "reset_fault_events",
           "faults_env", "corrupt_shard"]

ENV_VAR = "PADDLE_TPU_FAULT_INJECT"


def faults_env(specs, env=None):
    """Render `{site: "kind[:arg]"}` (or tuple specs) into a copy of
    `env` (default os.environ) carrying PADDLE_TPU_FAULT_INJECT — the
    way a subprocess inherits an injection plan it cannot inherit as a
    Python context manager (the `kill -9` crash-consistency tests)."""
    parts = []
    for site, spec in specs.items():
        if isinstance(spec, (tuple, list)):
            spec = ":".join(str(s) for s in spec)
        parts.append(f"{site}={spec}")
    out = dict(os.environ if env is None else env)
    out[ENV_VAR] = ";".join(parts)
    return out


def corrupt_shard(ckpt_dir, step):
    """Corrupt the largest data file inside one checkpoint step dir —
    the deterministic 'one shard rotted' fixture. Returns the path
    corrupted. Skips our own integrity manifest so the corruption hits
    checkpoint DATA (the manifest then convicts it on restore).

    EVERY file tied for the largest size is corrupted: orbax's ocdbt
    layout stores the same shard bytes under both `d/` and
    `ocdbt.process_0/d/`, and glob's scandir order is
    filesystem-dependent — corrupting only whichever copy enumerates
    first can hit the redundant one, which orbax restores around,
    silently turning the fixture into a no-op (observed as a
    host-dependent test flake)."""
    step_dir = os.path.join(ckpt_dir, str(int(step)))
    if not os.path.isdir(step_dir):
        raise FileNotFoundError(f"no step dir {step_dir}")
    files = sorted(
        p for p in glob.glob(os.path.join(step_dir, "**"), recursive=True)
        if os.path.isfile(p) and not p.endswith("integrity.json"))
    if not files:
        raise FileNotFoundError(f"no data file under {step_dir}")
    best_size = max(os.path.getsize(p) for p in files)
    best = None
    for p in files:
        if os.path.getsize(p) == best_size:
            best = corrupt_file(p)
    return best
