"""paddle.optimizer (reference: python/paddle/optimizer/*).

Each optimizer = a pure update rule fused into one jitted multi-tensor step
(see optimizer.py). Numerics mirror the reference PHI kernels (e.g.
phi/kernels/*/adam_kernel*).
"""
from __future__ import annotations

import jax.numpy as jnp

from . import lr  # noqa: F401
from .lr import LRScheduler  # noqa: F401
from .optimizer import Optimizer

__all__ = ["Optimizer", "SGD", "Momentum", "Adagrad", "Adadelta", "RMSProp",
           "Adam", "AdamW", "Adamax", "Lamb", "Lars", "LBFGS", "lr"]


class SGD(Optimizer):
    def _update_rule(self, v, g, s, lr, m, static=None):
        return v - (lr * m) * g, s


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._multi_precision = multi_precision
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p._value)}

    def _update_rule(self, v, g, s, lr, m, static=None):
        mu = self._momentum
        vel = mu * s["velocity"] + g
        if self._use_nesterov:
            new_v = v - (lr * m) * (g + mu * vel)
        else:
            new_v = v - (lr * m) * vel
        return new_v, {"velocity": vel}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full_like(p._value, self._init_acc)}

    def _update_rule(self, v, g, s, lr, m, static=None):
        mom = s["moment"] + g * g
        new_v = v - (lr * m) * g / (jnp.sqrt(mom) + self._epsilon)
        return new_v, {"moment": mom}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._rho = rho

    def _init_state(self, p):
        return {"avg_sq_grad": jnp.zeros_like(p._value),
                "avg_sq_update": jnp.zeros_like(p._value)}

    def _update_rule(self, v, g, s, lr, m, static=None):
        rho, eps = self._rho, self._epsilon
        asg = rho * s["avg_sq_grad"] + (1 - rho) * g * g
        update = -jnp.sqrt(s["avg_sq_update"] + eps) / jnp.sqrt(asg + eps) * g
        asu = rho * s["avg_sq_update"] + (1 - rho) * update * update
        return v + (lr * m) * update, {"avg_sq_grad": asg,
                                       "avg_sq_update": asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _init_state(self, p):
        st = {"mean_square": jnp.zeros_like(p._value),
              "momentum": jnp.zeros_like(p._value)}
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(p._value)
        return st

    def _update_rule(self, v, g, s, lr, m, static=None):
        rho, eps, mu = self._rho, self._epsilon, self._momentum
        ms = rho * s["mean_square"] + (1 - rho) * g * g
        new_s = {"mean_square": ms}
        if self._centered:
            mg = rho * s["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - mg * mg + eps)
            new_s["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + eps)
        mom = mu * s["momentum"] + (lr * m) * g / denom
        new_s["momentum"] = mom
        return v - mom, new_s


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=None,
                 use_multi_tensor=False, name=None, amsgrad=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._multi_precision = multi_precision
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _init_state(self, p):
        return {"moment1": jnp.zeros_like(p._value),
                "moment2": jnp.zeros_like(p._value),
                "beta1_pow": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32)}

    def _update_rule(self, v, g, s, lr, m, static=None):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        b1p = s["beta1_pow"] * b1
        b2p = s["beta2_pow"] * b2
        m1 = b1 * s["moment1"] + (1 - b1) * g
        m2 = b2 * s["moment2"] + (1 - b2) * g * g
        lr_t = (lr * m) * jnp.sqrt(1 - b2p) / (1 - b1p)
        new_v = v - lr_t.astype(v.dtype) * m1 / (
            jnp.sqrt(m2) + eps * jnp.sqrt(1 - b2p).astype(v.dtype))
        return new_v, {"moment1": m1, "moment2": m2, "beta1_pow": b1p,
                       "beta2_pow": b2p}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=None, name=None,
                 amsgrad=False):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, name=name)
        self._multi_precision = multi_precision
        self._coeff = float(weight_decay) if not hasattr(
            weight_decay, "coeff") else weight_decay.coeff
        self._apply_decay_fn = apply_decay_param_fun
        self._decay_skip = set()
        if apply_decay_param_fun is not None and parameters is not None:
            for p in self._parameter_list:
                if not apply_decay_param_fun(p.name):
                    self._decay_skip.add(id(p))

    def _wd_coeff(self, p):
        return 0.0  # decoupled: not folded into grads

    def _param_static(self, p):
        if self._apply_decay_fn is not None:
            return bool(self._apply_decay_fn(p.name))
        return True

    def _update_rule(self, v, g, s, lr, m, static=None):
        if static is None or static:
            v = v * (1.0 - (lr * m) * self._coeff).astype(v.dtype)
        return super()._update_rule(v, g, s, lr, m)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _init_state(self, p):
        return {"moment": jnp.zeros_like(p._value),
                "inf_norm": jnp.zeros_like(p._value),
                "beta1_pow": jnp.ones((), jnp.float32)}

    def _update_rule(self, v, g, s, lr, m, static=None):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        b1p = s["beta1_pow"] * b1
        mom = b1 * s["moment"] + (1 - b1) * g
        inf = jnp.maximum(b2 * s["inf_norm"], jnp.abs(g) + eps)
        new_v = v - ((lr * m) / (1 - b1p)).astype(v.dtype) * mom / inf
        return new_v, {"moment": mom, "inf_norm": inf, "beta1_pow": b1p}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._multi_precision = multi_precision
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, p):
        return {"moment1": jnp.zeros_like(p._value),
                "moment2": jnp.zeros_like(p._value),
                "beta1_pow": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32)}

    def _param_static(self, p):
        if self._exclude_fn is None:
            return None
        return {"decay_on": not self._exclude_fn(getattr(p, "name", "")
                                                 or "")}

    def _update_rule(self, v, g, s, lr, m, static=None):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        wd = self._lamb_wd if (static or {}).get("decay_on", True) else 0.0
        b1p = s["beta1_pow"] * b1
        b2p = s["beta2_pow"] * b2
        m1 = b1 * s["moment1"] + (1 - b1) * g
        m2 = b2 * s["moment2"] + (1 - b2) * g * g
        m1h = m1 / (1 - b1p)
        m2h = m2 / (1 - b2p)
        r = m1h / (jnp.sqrt(m2h) + eps) + wd * v
        w_norm = jnp.sqrt(jnp.sum(v.astype(jnp.float32) ** 2))
        r_norm = jnp.sqrt(jnp.sum(r.astype(jnp.float32) ** 2))
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_v = v - (lr * m * ratio).astype(v.dtype) * r
        return new_v, {"moment1": m1, "moment2": m2, "beta1_pow": b1p,
                       "beta2_pow": b2p}


class Lars(Optimizer):
    """LARS momentum (reference: incubate LarsMomentumOptimizer /
    fleet meta_optimizers/lars_optimizer.py): layer-wise adaptive rate —
    local_lr = lr * coeff * ||w|| / (||g|| + wd * ||w|| + eps), then a
    plain momentum update on (g + wd * w)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 epsilon=0.0, exclude_from_weight_decay=None,
                 multi_precision=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._multi_precision = multi_precision
        self._momentum = momentum
        self._coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._eps = epsilon
        self._exclude = list(exclude_from_weight_decay or [])

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p._value)}

    def _param_static(self, p):
        # excluded params (by name substring) keep the adaptive ratio but
        # drop weight decay — the reference kernel always applies the
        # ratio and only zeroes _lars_weight_decay for excluded params
        name = getattr(p, "name", "") or ""
        excluded = any(tok in name for tok in self._exclude)
        return {"decay_on": not excluded}

    def _update_rule(self, v, g, s, lr, m, static=None):
        wd = self._lars_wd if (static or {}).get("decay_on", True) else 0.0
        w_norm = jnp.sqrt(jnp.sum(v.astype(jnp.float32) ** 2))
        g_norm = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
        ratio = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._coeff * w_norm / (g_norm + wd * w_norm + self._eps),
            1.0)
        vel = self._momentum * s["velocity"] + (lr * m * ratio) * (g + wd * v)
        return v - vel.astype(v.dtype), {"velocity": vel}


class LBFGS(Optimizer):
    """Minimal LBFGS (reference: incubate/optimizer/lbfgs.py): closure-based."""

    def __init__(self, learning_rate=1.0, max_iter=20, history_size=100,
                 parameters=None, weight_decay=None, grad_clip=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 line_search_fn=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._max_iter = max_iter
        self._history = []
        self._prev_flat_g = None
        self._prev_flat_x = None
        self._hist_size = history_size

    def step(self, closure=None):
        import jax

        if closure is not None:
            closure()
        params = [p for p in self._param_list
                  if not p.stop_gradient and p._grad is not None]
        if not params:
            return
        flat_g = jnp.concatenate([p._grad._value.ravel().astype(jnp.float32)
                                  for p in params])
        flat_x = jnp.concatenate([p._value.ravel().astype(jnp.float32)
                                  for p in params])
        if self._prev_flat_g is not None:
            sk = flat_x - self._prev_flat_x
            yk = flat_g - self._prev_flat_g
            if float(sk @ yk) > 1e-10:
                self._history.append((sk, yk))
                if len(self._history) > self._hist_size:
                    self._history.pop(0)
        q = flat_g
        alphas = []
        for sk, yk in reversed(self._history):
            rho = 1.0 / (sk @ yk)
            a = rho * (sk @ q)
            q = q - a * yk
            alphas.append((a, rho, sk, yk))
        if self._history:
            sk, yk = self._history[-1]
            q = q * ((sk @ yk) / (yk @ yk))
        for a, rho, sk, yk in reversed(alphas):
            b = rho * (yk @ q)
            q = q + (a - b) * sk
        direction = -q
        self._prev_flat_g, self._prev_flat_x = flat_g, flat_x
        lr = self.get_lr()
        new_flat = flat_x + lr * direction
        off = 0
        for p in params:
            n = p.size
            p._value = new_flat[off:off + n].reshape(p._value.shape).astype(
                p._value.dtype)
            off += n
        self._global_step += 1
