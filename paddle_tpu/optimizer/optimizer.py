"""Optimizer base (reference: python/paddle/optimizer/optimizer.py).

TPU-native: every optimizer defines a pure per-param update rule; `step()`
runs ONE jitted multi-tensor update over all params/grads/states (buffer-
donated, so XLA updates in place in HBM) — the analogue of the reference's
fused/multi_tensor kernels, but compiler-scheduled.
"""
from __future__ import annotations

import functools
import itertools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..core import fusion as _fusion
from ..core.fusion import concrete as _concrete
from ..core.tensor import Tensor
from ..nn.clip import ClipGradBase
from ..runtime import tracing as _tracing
from .lr import LRScheduler

__all__ = ["Optimizer", "set_fused_step_recording"]

# Opt-in (PADDLE_TPU_FUSION_OPT_STEP=1): with trace fusion on, step()
# RECORDS the fused multi-tensor update into the lazy trace instead of
# concretizing at its boundary — the whole train step (fwd + bwd +
# optimizer) then flushes as ONE program at the caller's first host
# read (ROADMAP item 2's one-flush-per-step goal). Off by default: a
# loop that never reads a host value would otherwise accumulate ops
# across steps until the max_len valve, changing today's deterministic
# one-flush-per-step fingerprint pattern.
_fuse_step = [os.environ.get("PADDLE_TPU_FUSION_OPT_STEP", "0").lower()
              not in ("0", "false", "no")]
# monotonic serial per recorded step entry: the record_call key must
# uniquely name the emitted program, and a serial can never be recycled
# into aliasing a dead optimizer's cached fused program — unlike id(),
# which would otherwise force pinning the state-laden raw closure (and
# with it the whole optimizer's params/master weights) for the process
# lifetime. itertools.count.__next__ is one C-level call — atomic under
# the GIL, so two optimizers minting serials concurrently never collide.
_step_serial = itertools.count(1)


def set_fused_step_recording(mode):
    """Runtime analogue of ``PADDLE_TPU_FUSION_OPT_STEP``. Returns the
    previous mode."""
    prev = _fuse_step[0]
    _fuse_step[0] = bool(mode)
    return prev


class Optimizer:
    _state_names = ()  # per-param state slot names

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=None, **kwargs):
        self._multi_precision = multi_precision
        if parameters is not None:
            parameters = list(parameters)
            if parameters and isinstance(parameters[0], dict):
                # param groups: flatten; per-group learning_rate acts as a
                # multiplier on the base lr (stored in optimize_attr, same
                # mechanism as ParamAttr.learning_rate), per-group
                # weight_decay overrides the optimizer-level one.
                self._param_groups = parameters
                flat = []
                for g in parameters:
                    g_lr = g.get("learning_rate")
                    g_wd = g.get("weight_decay")
                    for p in g["params"]:
                        if g_lr is not None:
                            p.optimize_attr["learning_rate"] = float(g_lr)
                        if g_wd is not None:
                            from ..framework.param_attr import L2Decay

                            p.regularizer = g_wd if hasattr(g_wd, "coeff") \
                                else L2Decay(float(g_wd))
                        flat.append(p)
                parameters = flat
            else:
                self._param_groups = None
        self._parameter_list = parameters
        self._learning_rate = learning_rate
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._accumulators = {}   # param id -> {slot: jnp array}
        self._global_step = 0
        self._step_fn_cache = {}
        self._record_sigs = {}    # id(raw) -> ((treedef, avals), call,
        #                            out_avals, out_treedef) memo for the
        #                            trace-fusion record path
        self._step_recorded = False  # first step() recorded its warm-start
        #                              signature (even if warm_start built
        #                              the entry first)
        self._name = name or type(self).__name__

    # ---- lr ------------------------------------------------------------
    def get_lr(self):
        lr = self._learning_rate
        if isinstance(lr, LRScheduler):
            return float(lr())
        return float(lr)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "can't set_lr when learning rate is an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # ---- state ---------------------------------------------------------
    def _init_state(self, p):
        """Returns dict of state arrays for one param. Override."""
        return {}

    def _states_for(self, p):
        st = self._accumulators.get(id(p))
        if st is None:
            st = self._mp_init(p)
            self._accumulators[id(p)] = st
        return st

    _HALF_DTYPES = ("bfloat16", "float16")
    # reference multi_precision (python/paddle/optimizer/adamw.py):
    # None = AUTO (on for half params — the TPU-correct default: bf16
    # moment2 underflows since (1-b2)*g^2 vanishes below ~2^-8 relative,
    # and ~lr-magnitude updates round away against bf16 weights);
    # explicit False disables (halves optimizer-state HBM, reference
    # default behavior); True forces (no-op for f32 params).
    _multi_precision = None

    def _mp_init(self, p):
        """State init with multi_precision master-weight semantics:
        accumulators shaped like a half param are kept in f32 and an f32
        master copy carries the true weights. The param itself stays
        half; the master is state (sharded/checkpointed with it)."""
        st = self._init_state(p)
        v = p._value
        is_half = str(v.dtype) in self._HALF_DTYPES
        mp = self._multi_precision
        if (is_half if mp is None else (mp and is_half)):
            st = {k: (a.astype(jnp.float32)
                      if hasattr(a, "dtype") and a.dtype == v.dtype else a)
                  for k, a in st.items()}
            st["master"] = v.astype(jnp.float32)
        return st

    def _apply_rule(self, v, g, s, lr, mult, static):
        """Route the update through the f32 master when one exists; the
        caller downcasts the returned value to the param dtype."""
        master = s.get("master") if isinstance(s, dict) else None
        if master is not None:
            nv, ns = self._update_rule(master, g.astype(jnp.float32), s,
                                       lr, mult, static)
            ns = dict(ns)
            ns["master"] = nv
            return nv, ns
        return self._update_rule(v, g, s, lr, mult, static)

    def _update_rule(self, value, grad, state, lr, lr_mult, static=None):
        """Pure: (value, grad, state dict, lr scalar) -> (new_value, new_state).
        Override per optimizer. `static` carries trace-time per-param options
        from _param_static (e.g. AdamW decay exclusion)."""
        raise NotImplementedError

    def _param_static(self, p):
        """Static per-param options baked into the fused step at trace time."""
        return None

    # ---- regularization -------------------------------------------------
    def _wd_coeff(self, p):
        """L2-style decay folded into grads (non-decoupled optimizers)."""
        reg = getattr(p, "regularizer", None)
        if reg is not None:
            from ..framework.param_attr import L2Decay

            return reg.coeff if isinstance(reg, L2Decay) else 0.0
        wd = self._weight_decay
        if wd is None:
            return 0.0
        if isinstance(wd, float) or isinstance(wd, int):
            return float(wd)
        from ..framework.param_attr import L2Decay

        if isinstance(wd, L2Decay):
            return wd.coeff
        return 0.0

    def _l1_coeff(self, p):
        from ..framework.param_attr import L1Decay

        reg = getattr(p, "regularizer", None)
        if isinstance(reg, L1Decay):
            return reg.coeff
        if isinstance(self._weight_decay, L1Decay):
            return self._weight_decay.coeff
        return 0.0

    # ---- the fused step -------------------------------------------------
    def _build_step_fn(self, n, lr_mults, wd_coeffs, l1_coeffs, clip,
                       need_clip_flags, statics):
        rule = self._apply_rule

        def fused(values, states, grads, lr):
            # fold regularization into grads — against the f32 master
            # when one exists, not the rounded half param (wd*v on the
            # bf16 view would re-introduce the quantization the master
            # pipeline removes)
            gs = []
            for g, v, s, wd, l1 in zip(grads, values, states, wd_coeffs,
                                       l1_coeffs):
                vv = s.get("master", v) if isinstance(s, dict) else v
                if wd:
                    g = g + wd * vv
                if l1:
                    g = g + l1 * jnp.sign(vv)
                gs.append(g)
            if clip is not None:
                clipped = clip.clip_values(
                    {i: g for i, (g, f) in enumerate(zip(gs, need_clip_flags))
                     if f})
                gs = [clipped.get(i, g) if need_clip_flags[i] else g
                      for i, g in enumerate(gs)]
            new_vals, new_states = [], []
            for v, s, g, m, st in zip(values, states, gs, lr_mults, statics):
                nv, ns = rule(v, g, s, lr, m, st)
                new_vals.append(nv.astype(v.dtype))
                new_states.append(ns)
            return new_vals, new_states

        # Donation contract: params + opt states are donated to XLA so the
        # update rewrites HBM in place. Any alias of the pre-step param
        # arrays (Tensor.detach() taken earlier, retained residuals for a
        # second backward of a freed graph) is invalidated by step(); callers
        # holding such aliases must materialize them first (see
        # Tensor.detach docstring). The RAW fn rides along for the
        # trace-fusion record path (a node call must not be a donating
        # jit — inside the fused program donation is meaningless and
        # jax warns).
        return jax.jit(fused, donate_argnums=(0, 1)), fused  # tracelint: ok[suspend-audit] raw-jnp update rules + clip_values

    @property
    def _param_list(self):
        if self._parameter_list is None:
            raise RuntimeError(
                "Optimizer created without parameters; pass parameters= or "
                "use minimize(loss, parameters=...)")
        return self._parameter_list

    def _entry_for(self, params):
        """The fused jitted step for this exact param list, built on
        first sight (shared by step() and warm_start())."""
        key = tuple(id(p) for p in params)
        entry = self._step_fn_cache.get(key)
        built = entry is None
        if built:
            lr_mults = tuple(
                float(getattr(p, "optimize_attr", {}).get("learning_rate", 1.0))
                for p in params)
            wd = tuple(self._wd_coeff(p) for p in params)
            l1 = tuple(self._l1_coeff(p) for p in params)
            flags = tuple(bool(getattr(p, "need_clip", True)) for p in params)
            statics = tuple(self._param_static(p) for p in params)
            clip = self._grad_clip if isinstance(self._grad_clip,
                                                 ClipGradBase) else None
            entry = self._build_step_fn(len(params), lr_mults, wd, l1, clip,
                                        flags, statics)
            self._step_fn_cache[key] = entry
        return entry, built

    def _program_name(self):
        return f"optimizer.fused_step.{type(self).__name__}"

    def _record_step(self, raw, values, states, grads, lr):
        """Defer the fused multi-tensor update into the trace-fusion
        lazy trace (PADDLE_TPU_FUSION_OPT_STEP): the step becomes one
        trace node consuming the deferred fwd/bwd placeholders, so the
        whole train step flushes as ONE program at the caller's first
        host read instead of concretizing here. Returns (new_vals,
        new_states) of LazyArrays, or None when fusion is not recording
        (the caller runs the jitted entry on concrete values)."""
        flat_in, in_treedef = jax.tree_util.tree_flatten(
            (values, states, grads, lr))
        sig = self._record_sigs.get(id(raw))
        avals = []
        try:
            for v in flat_in:
                avals.append((tuple(v.shape), np.dtype(v.dtype),
                              bool(getattr(v, "weak_type", False))))
        except (TypeError, AttributeError):
            return None  # a non-array leaf slipped in: concrete path
        avals = tuple(avals)
        if sig is None or sig[0] != (in_treedef, avals):
            structs = [jax.ShapeDtypeStruct(s, d, weak_type=w)
                       for (s, d, w) in avals]

            def natural(*leaves, _raw=raw, _td=in_treedef):
                v, s, g, l = jax.tree_util.tree_unflatten(_td, list(leaves))
                return _raw(v, s, g, l)

            def call(*leaves):
                return tuple(jax.tree_util.tree_flatten(
                    natural(*leaves))[0])

            try:
                out_struct = jax.eval_shape(natural, *structs)  # tracelint: ok[suspend-audit] raw fused update is pure jnp (same contract as _build_step_fn)
            except Exception:  # noqa: BLE001 — any abstract-eval issue
                # (exotic state leaf, shape error): decline, never break
                # the step; the concrete path raises the genuine error
                return None
            out_leaves, out_td = jax.tree_util.tree_flatten(out_struct)
            out_avals = tuple(
                (tuple(o.shape), np.dtype(o.dtype),
                 bool(getattr(o, "weak_type", False)))
                for o in out_leaves)
            sig = ((in_treedef, avals), call, out_avals, out_td,
                   next(_step_serial))
            self._record_sigs[id(raw)] = sig
        _, call, out_avals, out_td, serial = sig
        key = ("opt.fused_step", type(self).__name__, serial, in_treedef)
        lazy = _fusion.record_call(key, call, flat_in, out_avals,
                                   f"opt.{type(self).__name__}")
        if lazy is None:
            return None
        return jax.tree_util.tree_unflatten(out_td, lazy)

    def step(self):
        # span-tracer phase boundary: the optimizer update (and, under
        # fusion, the flush its _concrete boundary forces — a nested
        # span, so it is not double counted) as one "optimizer" span
        if not _tracing._on[0]:
            return self._step_impl()
        with _tracing.span("opt_step", "optimizer",
                           opt=type(self).__name__):
            return self._step_impl()

    def _step_impl(self):
        params = [p for p in self._param_list
                  if not p.stop_gradient and p._grad is not None
                  and getattr(p, "trainable", True)]
        if not params:
            return
        (entry, raw), built = self._entry_for(params)
        values = [p._value for p in params]
        states = [self._states_for(p) for p in params]
        grads = [p._grad._value.astype(
            jnp.float32 if "master" in s else p._value.dtype)
            for p, s in zip(params, states)]
        lr = jnp.asarray(self.get_lr(), jnp.float32)
        # PADDLE_TPU_FUSION_OPT_STEP: defer the update into the lazy
        # trace (one flush per step, at the caller's host read). The
        # first step of a fresh entry still takes the concrete path —
        # it must record the warm-start signature on real arrays.
        if _fuse_step[0] and _fusion.fusion_enabled() and \
                not built and self._step_recorded:
            out = self._record_step(raw, values, states, grads, lr)
            if out is not None:
                new_vals, new_states = out
                for p, nv, ns in zip(params, new_vals, new_states):
                    p._value = nv
                    self._accumulators[id(p)] = ns
                self._global_step += 1
                return
        # the fused multi-tensor step is the train step's natural
        # trace-fusion flush boundary: the casts above were RECORDED
        # (not executed) when fusion is on, so the first _concrete
        # lands the whole deferred fwd+bwd+casts as ONE fused program
        # and the rest are lookups. Handing still-lazy leaves to the
        # jitted entry instead would defeat pjit's C++ arg cache and
        # retrace the optimizer step every call.
        values = [_concrete(v) for v in values]  # fuselint: ok[FL001] the reviewed per-step flush boundary (PADDLE_TPU_FUSION_OPT_STEP defers it)
        grads = [_concrete(g) for g in grads]  # fuselint: ok[FL001] see above — one intentional materialize per step
        # first step of a freshly built OR warm-started entry (built is
        # False after warm_start pre-built it): trace + compile/disk
        # load happens now — attribute the time and record the
        # signature for the warm-start manifest BEFORE the call, since
        # values/states are donated (dead afterwards)
        if built or not self._step_recorded:
            self._step_recorded = True
            from ..runtime import warmup as _warmup

            _warmup.record_program(self._program_name(),
                                   (values, states, grads, lr))
            t0 = time.perf_counter()
            new_vals, new_states = entry(values, states, grads, lr)
            _warmup.note_op_compile(self._program_name(),
                                    time.perf_counter() - t0)
            _warmup.note_first_step("fused_step")
        else:
            new_vals, new_states = entry(values, states, grads, lr)
        for p, nv, ns in zip(params, new_vals, new_states):
            p._value = nv
            self._accumulators[id(p)] = ns
        self._global_step += 1

    def warm_start(self, manifest=None):
        """AOT-precompile the fused multi-tensor step for the CURRENT
        parameter list, plus any signatures recorded for this optimizer
        class in a warm-start manifest (runtime/warmup.py). Grad avals
        are synthesized from the params (f32 when a master weight
        exists), so no backward pass is needed — with the persistent
        compile cache enabled the XLA work is a disk load and the first
        real step pays retrace only. Returns the number of signatures
        compiled.

        Best-effort: the entry is built for ALL trainable params (grads
        do not exist yet), while step() keys on the grad-bearing
        subset. If some trainable param never receives a grad (unused
        by the loss), the first real step builds its own entry — still
        a disk-cache load for the XLA portion when shapes coincide,
        a plain cold compile otherwise."""
        from ..runtime import warmup as _warmup

        if manifest is not None:
            _warmup.precompile(manifest)
        params = [p for p in self._param_list
                  if not p.stop_gradient and getattr(p, "trainable", True)]
        n = 0
        if params:
            (entry, _raw), _ = self._entry_for(params)
            n += _warmup.prewarm_program(self._program_name(), entry)
            if n:
                # the recorded signature already covered this optimizer;
                # the self-derived lowering below would trace the same
                # program a second time (the dominant warm-start cost
                # host-side)
                return n
            try:
                values = [jax.ShapeDtypeStruct(p._value.shape,
                                               p._value.dtype)
                          for p in params]
                states = [self._states_for(p) for p in params]
                grads = [jax.ShapeDtypeStruct(
                    p._value.shape,
                    jnp.float32 if "master" in s else p._value.dtype)
                    for p, s in zip(params, states)]
                lr = jax.ShapeDtypeStruct((), jnp.float32)
                t0 = time.perf_counter()
                entry.lower(values, states, grads, lr).compile()
                _warmup.note_op_compile(self._program_name(),
                                        time.perf_counter() - t0)
                n += 1
            except Exception:  # noqa: BLE001 — warm-start is best-effort
                from ..runtime.resilience import record_fault

                record_fault("stale_manifests",
                             f"{self._program_name()}: self-derived "
                             "signature failed to lower")
        return n

    def clear_grad(self, set_to_zero=True):
        for p in self._param_list:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..framework.mode import in_static_mode

        if in_static_mode():
            # record into the program; Executor folds backward+update into
            # the jitted whole-program replay
            from ..static.program import default_main_program

            prog = default_main_program()
            prog.minimize_records.append((self, loss))
            return None, [(p, None) for p in prog.all_parameters()]
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._param_list]

    # ---- state dict ------------------------------------------------------
    def state_dict(self):
        sd = {}
        for i, p in enumerate(self._param_list):
            st = self._accumulators.get(id(p))
            if st:
                for k, v in st.items():
                    sd[f"{p.name}_{k}"] = Tensor(v)
        sd["global_step"] = self._global_step
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        for p in self._param_list:
            st = self._states_for(p)
            new = {}
            for k in st:
                key = f"{p.name}_{k}"
                if key in state_dict:
                    v = state_dict[key]
                    new[k] = v._value if isinstance(v, Tensor) else jnp.asarray(v)
                else:
                    new[k] = st[k]
            self._accumulators[id(p)] = new
        self._global_step = int(state_dict.get("global_step", 0))
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate,
                                                       LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])

    load_state_dict = set_state_dict

    # functional access for hapi's fully-jitted train step ----------------
    def param_meta(self, named_params):
        """Static per-param options for the functional path, keyed like the
        values tree: {name: (wd, l1, lr_mult, need_clip, static)}."""
        return {
            name: (self._wd_coeff(p), self._l1_coeff(p),
                   float(getattr(p, "optimize_attr", {}).get(
                       "learning_rate", 1.0)),
                   bool(getattr(p, "need_clip", True)),
                   self._param_static(p))
            for name, p in named_params.items()
        }

    def functional_update(self, values_tree, grads_tree, states_tree, lr,
                          meta=None, clip=None):
        """Pure pytree update used by hapi Model — applies the SAME
        regularization-fold -> clip -> rule sequence as the fused step()."""
        leaves_v, treedef = jax.tree_util.tree_flatten(values_tree)
        leaves_g = treedef.flatten_up_to(grads_tree)
        metas = treedef.flatten_up_to(meta) if meta is not None else \
            [(0.0, 0.0, 1.0, True, None)] * len(leaves_v)
        leaves_s = [states_tree[i] for i in range(len(leaves_v))]
        gs = []
        for v, g, s, (wd, l1, _, _, _) in zip(leaves_v, leaves_g, leaves_s,
                                              metas):
            # with a master the rule runs in f32 — downcasting an f32
            # grad to the half param dtype here would throw away the
            # very mantissa the master pipeline preserves; the decay
            # fold likewise uses the master, not the rounded half view
            has_master = isinstance(s, dict) and "master" in s
            g = g.astype(jnp.float32 if has_master else v.dtype)
            vv = s["master"] if has_master else v
            if wd:
                g = g + wd * vv
            if l1:
                g = g + l1 * jnp.sign(vv)
            gs.append(g)
        if clip is not None:
            flags = [m[3] for m in metas]
            clipped = clip.clip_values(
                {i: g for i, (g, f) in enumerate(zip(gs, flags)) if f})
            gs = [clipped.get(i, g) if flags[i] else g
                  for i, g in enumerate(gs)]
        new_v, new_s = [], []
        for v, g, s, (_, _, mult, _, static) in zip(leaves_v, gs, leaves_s,
                                                    metas):
            nv, ns = self._apply_rule(v, g, s, lr, mult, static)
            new_v.append(nv.astype(v.dtype))
            new_s.append(ns)
        return jax.tree_util.tree_unflatten(treedef, new_v), \
            {i: s for i, s in enumerate(new_s)}

    def functional_init_states(self, values_tree):
        leaves, _ = jax.tree_util.tree_flatten(values_tree)
        return {i: self._init_state_value(v) for i, v in enumerate(leaves)}

    def _init_state_value(self, value):
        p = Tensor(value)
        return self._mp_init(p)
