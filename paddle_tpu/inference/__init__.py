"""Paddle Inference — TPU-native serving.

Two tiers live here:

* **Predictor API** (`predictor.py`) — reference-parity
  Config/Predictor/Tensor handles over a jit.save artifact: one AOT
  program per input signature, for offline batch inference.
* **Serving engine** (`engine.py` + `scheduler.py` + `kv_cache.py` +
  `model.py`) — the online tier: a block-allocated paged KV cache, a
  continuous-batching scheduler assembling padding-free ragged batches
  per decode iteration, and ragged/paged attention
  (nn/functional/attention.py dense path; ops/pallas decode kernel on
  TPU). See docs/SERVING.md.
"""
from __future__ import annotations

from .access_log import AccessLog, read_access_log, tail_sampled  # noqa: F401
from .engine import ServeConfig, ServingEngine  # noqa: F401
from .journal import RequestJournal, read_journal  # noqa: F401
from .kv_cache import KVCacheConfig, PagedKVCache  # noqa: F401
from .model import TinyServeModel  # noqa: F401
from .predictor import (  # noqa: F401
    Config,
    DataType,
    PlaceType,
    PrecisionType,
    Predictor,
    PredictorPool,
    Tensor,
    create_predictor,
    get_num_bytes_of_data_type,
    get_trt_compile_version,
    get_trt_runtime_version,
    get_version,
)
from .scheduler import (  # noqa: F401
    ContinuousBatchingScheduler,
    OverloadedError,
    RequestState,
    ServeRequest,
    StepPlan,
)

__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "PrecisionType", "PlaceType", "get_version", "DataType",
           "PredictorPool", "get_num_bytes_of_data_type",
           "get_trt_compile_version", "get_trt_runtime_version",
           "ServingEngine", "ServeConfig", "PagedKVCache", "KVCacheConfig",
           "ContinuousBatchingScheduler", "ServeRequest", "RequestState",
           "StepPlan", "TinyServeModel", "OverloadedError",
           "RequestJournal", "read_journal",
           "AccessLog", "read_access_log", "tail_sampled"]
