"""Continuous-batching serving engine (ROADMAP item 1).

`ServingEngine` drives the decode loop: per iteration the scheduler
assembles a ragged batch (mixed prefill chunks + decode tokens over the
paged KV cache), the model runs it as `apply`-dispatched ops (jit-cached
per-op, or ONE fused program per step under
``PADDLE_TPU_EAGER_FUSION=1``), greedy sampling host-reads the step's
emitted tokens (the step's single device sync — and, under fusion, its
single flush site), and the scheduler applies them.

Runtime-spine reuse:

* **warm start** — every op the step compiles lands in the shape
  manifest like any other dispatch traffic; `warm_start()` replays it
  so a restarted server performs ZERO fresh XLA compiles
  (tools/serve_smoke.py gates this).
* **telemetry** — `paddle_tpu_serve_request_seconds` and
  `paddle_tpu_serve_ttft_seconds` histograms plus request/token
  counters and a tokens/sec gauge, every histogram fed from the SAME
  measured duration as its `serve/` span, so
  `tracing.reconcile_with_metrics` agreement is exact.
* **tracing** — `serve/serve_step` spans wrap each iteration (nested
  dispatch/fusion spans decompose it); `serve/request` and
  `serve/ttft` spans are emitted per request from the histogram
  measurement.
* **resilience** — per-request deadlines evict through the scheduler
  (``request_deadline`` fault events); an optional ElasticManager is
  ticked per iteration so the existing watchdog arms against a WEDGED
  loop (`step_deadline`) exactly as it does for training; a
  ``serve.step`` fault-point lets FaultInjector wedge the loop in
  tests.
"""
from __future__ import annotations

import time

import numpy as np

from ..io import prefetch as _prefetch
from ..runtime import diagnostics as _diagnostics
from ..runtime import telemetry as _telemetry
from ..runtime import tracing as _tracing
from ..runtime.resilience import fault_point
from .kv_cache import PagedKVCache
from .scheduler import ContinuousBatchingScheduler, ServeRequest

__all__ = ["ServeConfig", "ServingEngine"]

_LATENCY_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class ServeConfig:
    """Engine knobs. `token_budget` is the ragged rows per step (the
    fixed batch shape); `max_running` the concurrent-request slots;
    block geometry comes from the model's `kv_config`."""

    def __init__(self, max_running=4, token_budget=16, block_size=16,
                 num_blocks=64, max_blocks_per_seq=None,
                 default_deadline_s=None, max_steps=10000):
        self.max_running = int(max_running)
        self.token_budget = int(token_budget)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_blocks_per_seq = max_blocks_per_seq
        self.default_deadline_s = default_deadline_s
        self.max_steps = int(max_steps)


class ServingEngine:
    def __init__(self, model, config=None, elastic=None):
        self.model = model
        self.config = config or ServeConfig()
        self.cache = PagedKVCache(model.kv_config(
            block_size=self.config.block_size,
            num_blocks=self.config.num_blocks,
            max_blocks_per_seq=self.config.max_blocks_per_seq))
        self.scheduler = ContinuousBatchingScheduler(
            self.cache, max_running=self.config.max_running,
            token_budget=self.config.token_budget,
            default_deadline_s=self.config.default_deadline_s)
        self.elastic = elastic          # optional watchdog/heartbeat
        self.steps = 0
        self._busy_s = 0.0
        self._tokens_out = 0
        self._evicted_seen = 0
        # device-resident padded block tables, keyed on the KV
        # allocator's mutation version + the slot occupancy: prefill
        # admission / eviction invalidates, steady-state decode steps
        # reuse — retiring the one per-step H2D transfer whose payload
        # almost never changes (io/prefetch.py is the shared h2d lane)
        self._tables_dev = None
        self._tables_key = None
        self._results = {}        # finished, not yet drained by run()
        self._results_limit = 4096
        self._h_request = _telemetry.histogram(
            "paddle_tpu_serve_request_seconds",
            "submit-to-finish latency per served request",
            buckets=_LATENCY_BUCKETS)
        self._h_ttft = _telemetry.histogram(
            "paddle_tpu_serve_ttft_seconds",
            "submit-to-first-token latency per served request",
            buckets=_LATENCY_BUCKETS)
        self._c_req = _telemetry.counter(
            "paddle_tpu_serve_requests_total",
            "requests leaving the engine, by outcome", ("outcome",))
        self._c_tok = _telemetry.counter(
            "paddle_tpu_serve_tokens_total", "generated tokens")
        self._c_steps = _telemetry.counter(
            "paddle_tpu_serve_steps_total",
            "decode-loop iterations, by batch kind", ("kind",))
        self._g_tps = _telemetry.gauge(
            "paddle_tpu_serve_tokens_per_sec",
            "generated tokens per busy second (cumulative)")
        # crash-and-hang observability: the /serving statusz route and
        # postmortem bundles report this engine's scheduler + KV-pool
        # state (weak registration — the engine's lifetime is its own),
        # and a server process with PADDLE_TPU_DIAGNOSTICS_DIR set arms
        # bundles-on-fatal-signal for its decode loop
        _diagnostics.register_serving_engine(self)
        _diagnostics.ensure_installed()

    # -- request API --------------------------------------------------------

    def submit(self, prompt, max_new_tokens=16, deadline_s=None,
               eos_id=None, request_id=None):
        """Queue one request; returns its id."""
        req = ServeRequest(prompt, max_new_tokens=max_new_tokens,
                           deadline_s=deadline_s, eos_id=eos_id,
                           request_id=request_id)
        self.scheduler.submit(req)
        return req.request_id

    def step(self):
        """One decode-loop iteration. Returns False when no work ran
        (idle queue and no running requests)."""
        from ..core.autograd import apply, no_grad
        from ..core.tensor import Tensor

        t0 = time.perf_counter()
        fault_point("serve.step", step=self.steps)
        plan = self.scheduler.plan(now=t0)
        if plan.n_rows == 0:
            # deadline sweeps may still have evicted queued requests
            self._account_evicted()
            return False
        with _tracing.span("serve_step", "serve", rows=plan.n_rows,
                           decode=plan.decode_rows,
                           prefill=plan.prefill_rows):
            tables = self._device_tables()
            # the step's ragged inputs go through the shared h2d lane
            # (histogram + io/h2d span from one measurement), same as
            # the training prefetcher's commits
            tok_a, rreq_a, rpos_a = _prefetch.commit_arrays(
                [plan.token_ids, plan.row_req, plan.row_pos],
                kind="serve_step")
            tok = Tensor(tok_a)
            rreq = Tensor(rreq_a)
            rpos = Tensor(rpos_a)
            with no_grad():
                logits = self.model.forward(
                    tok, rreq, rpos, self.cache, tables,
                    decode_only=plan.decode_only)
                sampled = apply(_greedy_sample, logits)
            # THE step sync: one host read of the sampled tokens (under
            # fusion, the step's single flush site)
            tokens = np.asarray(sampled._value)  # fuselint: ok[FL001] the decode loop's one intended per-step sync
        now = time.perf_counter()
        finished = self.scheduler.complete_step(plan, tokens, now=now)
        self.steps += 1
        self._busy_s += now - t0
        self._tokens_out += len(plan.emit)
        self._c_tok.inc(len(plan.emit))
        self._c_steps.labels(
            kind="decode" if plan.decode_only else "mixed").inc()
        for _row, req in plan.emit:
            if req.t_first_token is not None and len(req.generated) == 1:
                dt = req.t_first_token - req.t_submit
                self._h_ttft.observe(dt)
                _tracing.emit_span("ttft", "serve", req.t_submit_wall,
                                   dt, request=req.request_id)
        for req in finished:
            dt = req.t_done - req.t_submit
            self._h_request.observe(dt)
            _tracing.emit_span("request", "serve", req.t_submit_wall, dt,
                               request=req.request_id,
                               tokens=len(req.generated))
            self._c_req.labels(outcome="completed").inc()
            # results parked until the next run() drains them (bounded
            # like the scheduler history — a step()-loop caller that
            # never drains must not grow memory per request served)
            self._results[req.request_id] = list(req.generated)
            while len(self._results) > self._results_limit:
                self._results.pop(next(iter(self._results)))
        self._account_evicted()
        if self._busy_s > 0:
            self._g_tps.set(self._tokens_out / self._busy_s)
        if self.elastic is not None:
            try:
                self.elastic.tick(self.steps)
            except Exception:  # noqa: BLE001 — liveness must not kill serving
                pass
        return True

    def _device_tables(self):
        """The padded block-table matrix, committed once per
        (allocation version, slot occupancy) — admission, growth, and
        eviction invalidate; pure decode steps reuse the device copy
        instead of re-transferring an identical matrix every step."""
        from ..core.tensor import Tensor

        running = self.scheduler.running
        ids = tuple(running[s].request_id if s in running else None
                    for s in range(self.config.max_running))
        key = (self.cache.alloc_version(), ids)
        if self._tables_dev is None or key != self._tables_key:
            arr = self.cache.padded_tables(list(ids))
            self._tables_dev = Tensor(
                _prefetch.commit_arrays([arr], kind="serve_tables")[0])
            self._tables_key = key
        return self._tables_dev

    def _account_evicted(self):
        # the scheduler's evicted deque is bounded; count by total and
        # read the newest entries (per-step evictions are far below the
        # history bound, so none rotate out before this runs)
        new = self.scheduler.evicted_total - self._evicted_seen
        if new <= 0:
            return
        self._evicted_seen = self.scheduler.evicted_total
        for req in list(self.scheduler.evicted)[-new:]:
            self._c_req.labels(outcome="evicted").inc()
            # an evicted request still closes its latency span — the
            # operator's histogram covers every request that LEFT, not
            # only the happy path (outcome label tells them apart)
            dt = time.perf_counter() - req.t_submit
            self._h_request.observe(dt)
            _tracing.emit_span("request", "serve", req.t_submit_wall, dt,
                               request=req.request_id, evicted=True)

    def run(self, max_steps=None):
        """Drive `step()` until the queue drains (or `max_steps`).
        Returns {request_id: generated token list} for every request
        that finished since the previous `run()` call drained them."""
        limit = max_steps if max_steps is not None else self.config.max_steps
        steps = 0
        while self.scheduler.has_work() and steps < limit:
            if not self.step():
                if not self.scheduler.has_work():
                    break
            steps += 1
        out, self._results = self._results, {}
        return out

    def generate(self, prompts, max_new_tokens=16, **kw):
        """Convenience: submit `prompts` (list of token lists), run to
        completion, return generated tokens in submission order."""
        ids = [self.submit(p, max_new_tokens=max_new_tokens, **kw)
               for p in prompts]
        out = self.run()
        return [out.get(i) for i in ids]

    # -- warm start ---------------------------------------------------------

    def warm_start(self, manifest_path=None):
        """AOT-precompile the shape manifest (path, or the
        ``PADDLE_TPU_SHAPE_MANIFEST`` env default) so a restarted server
        process performs zero fresh XLA compiles. Returns the precompile
        stats dict."""
        from ..runtime import warmup as _warmup

        doc = _warmup.load_manifest(manifest_path)
        return _warmup.precompile(doc)

    def stats(self):
        s = self.scheduler.stats()
        s.update(steps=self.steps, busy_s=self._busy_s,
                 tokens_out=self._tokens_out,
                 tokens_per_sec=(self._tokens_out / self._busy_s
                                 if self._busy_s else 0.0))
        return s

    def diagnostics_snapshot(self):
        """Engine + scheduler + KV-pool state for the diagnostics layer
        (the /serving statusz route and postmortem bundles): live
        request ids with their progress, pool occupancy, and the
        engine-level throughput counters — enough to see WHAT a wedged
        or dying server was doing, without touching device state."""
        # called from the statusz/watchdog threads while the engine
        # thread mutates scheduler state: copy the dict FIRST (a C-level
        # atomic) so iteration can never race an admit/evict resize
        running = dict(self.scheduler.running)
        return {
            "config": {"max_running": self.config.max_running,
                       "token_budget": self.config.token_budget,
                       "block_size": self.config.block_size,
                       "num_blocks": self.config.num_blocks},
            "stats": self.stats(),
            "kv": {"blocks_free": self.cache.blocks_free(),
                   "blocks_in_use": self.cache.blocks_in_use(),
                   "utilization": self.cache.utilization()},
            "running": [
                {"request_id": req.request_id, "slot": slot,
                 "prompt_len": len(req.prompt),
                 "generated": len(req.generated),
                 "max_new_tokens": req.max_new_tokens}
                for slot, req in sorted(running.items())],
            "queued": len(self.scheduler.queue),
            "undrained_results": len(self._results),
        }


def _greedy_sample(lg):
    import jax.numpy as jnp

    return jnp.argmax(lg, axis=-1).astype(jnp.int32)


_greedy_sample.__name__ = "serve_greedy_sample"  # dispatch/AMP key name
