"""Continuous-batching serving engine (ROADMAP item 1).

`ServingEngine` drives the decode loop: per iteration the scheduler
assembles a ragged batch (mixed prefill chunks + decode tokens over the
paged KV cache), the model runs it as `apply`-dispatched ops (jit-cached
per-op, or ONE fused program per step under
``PADDLE_TPU_EAGER_FUSION=1``), greedy sampling host-reads the step's
emitted tokens (the step's single device sync — and, under fusion, its
single flush site), and the scheduler applies them.

Runtime-spine reuse:

* **warm start** — every op the step compiles lands in the shape
  manifest like any other dispatch traffic; `warm_start()` replays it
  so a restarted server performs ZERO fresh XLA compiles
  (tools/serve_smoke.py gates this).
* **telemetry** — `paddle_tpu_serve_request_seconds` and
  `paddle_tpu_serve_ttft_seconds` histograms plus request/token
  counters and a tokens/sec gauge, every histogram fed from the SAME
  measured duration as its `serve/` span, so
  `tracing.reconcile_with_metrics` agreement is exact.
* **tracing** — `serve/serve_step` spans wrap each iteration (nested
  dispatch/fusion spans decompose it); `serve/request` and
  `serve/ttft` spans are emitted per request from the histogram
  measurement.
* **resilience** — per-request deadlines evict through the scheduler
  (``request_deadline`` fault events); an optional ElasticManager is
  ticked per iteration so the existing watchdog arms against a WEDGED
  loop (`step_deadline`) exactly as it does for training; a
  ``serve.step`` fault-point lets FaultInjector wedge the loop in
  tests.

Robustness layer (ISSUE 18):

* **overload** — `submit()` raises `OverloadedError` (outcome counter
  label ``overloaded``, ``serve_sheds`` fault) when the scheduler's
  bounded queue refuses admission; the engine never grows memory with
  arrival rate and never wedges (see docs/SERVING.md failure matrix).
* **lifecycle** — `cancel(id)` frees a request's KV blocks NOW;
  `drain(deadline_s)` stops admission, finishes accepted work, and
  evicts the stragglers at the deadline; `install_signal_drain()` wires
  drain into the PR-14 SIGTERM path (drain, postmortem bundle, then
  the default termination semantics — rc is still ``-SIGTERM``).
* **crash recovery** — an optional `RequestJournal` records every
  admitted request and emitted token; `recover()` re-admits a crashed
  process's unfinished tail with the already-generated tokens as
  context, so greedy determinism + `warm_start()` resume token-exact
  with zero fresh compiles (tools/serve_chaos_smoke.py gates this).
"""
from __future__ import annotations

import os
import signal as _signal
import time

import numpy as np

from ..io import prefetch as _prefetch
from ..runtime import diagnostics as _diagnostics
from ..runtime import telemetry as _telemetry
from ..runtime import tracing as _tracing
from ..runtime.resilience import fault_point
from ..runtime.windows import ServingWindows, SLOMonitor
from .access_log import AccessLog, tail_sampled
from .journal import RequestJournal, read_journal
from .kv_cache import PagedKVCache
from .scheduler import (ContinuousBatchingScheduler, OverloadedError,
                        RequestState, ServeRequest)

__all__ = ["ServeConfig", "ServingEngine", "OverloadedError"]

_LATENCY_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
# inter-token decode gaps live well below request latencies
_TPOT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5)


def _env_float(name, default):
    try:
        raw = os.environ.get(name)
        return default if raw is None else float(raw)
    except ValueError:
        return default


class ServeConfig:
    """Engine knobs. `token_budget` is the ragged rows per step (the
    fixed batch shape); `max_running` the concurrent-request slots;
    block geometry comes from the model's `kv_config`."""

    def __init__(self, max_running=4, token_budget=16, block_size=16,
                 num_blocks=64, max_blocks_per_seq=None,
                 default_deadline_s=None, max_steps=10000,
                 max_queued=256, max_queued_tokens=None,
                 max_queued_blocks=None, max_queue_wait_s=None,
                 drain_deadline_s=30.0, journal_max_bytes=4 << 20,
                 access_log=None, access_log_max_bytes=4 << 20,
                 trace_slow_s=None, slo_ttft_s=None,
                 slo_objective=0.99):
        self.max_running = int(max_running)
        self.token_budget = int(token_budget)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_blocks_per_seq = max_blocks_per_seq
        self.default_deadline_s = default_deadline_s
        self.max_steps = int(max_steps)
        # admission bounds (scheduler.py documents the semantics; None
        # scales the token/block bounds to the engine's capacity)
        self.max_queued = int(max_queued)
        self.max_queued_tokens = max_queued_tokens
        self.max_queued_blocks = max_queued_blocks
        self.max_queue_wait_s = max_queue_wait_s
        self.drain_deadline_s = float(drain_deadline_s)
        self.journal_max_bytes = int(journal_max_bytes)
        # request-scoped observability (ISSUE 20): access-log path (or
        # the PADDLE_TPU_SERVE_ACCESS_LOG env; None = ring+aggregates
        # only), the tail-sampling slow threshold, and the TTFT SLO the
        # burn-rate monitor evaluates
        self.access_log = access_log
        self.access_log_max_bytes = int(access_log_max_bytes)
        self.trace_slow_s = trace_slow_s
        self.slo_ttft_s = slo_ttft_s
        self.slo_objective = float(slo_objective)


class ServingEngine:
    def __init__(self, model, config=None, elastic=None, journal=None):
        self.model = model
        self.config = config or ServeConfig()
        self.cache = PagedKVCache(model.kv_config(
            block_size=self.config.block_size,
            num_blocks=self.config.num_blocks,
            max_blocks_per_seq=self.config.max_blocks_per_seq))
        self.scheduler = ContinuousBatchingScheduler(
            self.cache, max_running=self.config.max_running,
            token_budget=self.config.token_budget,
            default_deadline_s=self.config.default_deadline_s,
            max_queued=self.config.max_queued,
            max_queued_tokens=self.config.max_queued_tokens,
            max_queued_blocks=self.config.max_queued_blocks,
            max_queue_wait_s=self.config.max_queue_wait_s)
        self.elastic = elastic          # optional watchdog/heartbeat
        # crash-recovery journal: a RequestJournal, a path, or the
        # PADDLE_TPU_SERVE_JOURNAL env (None = journal-less serving)
        journal = journal or os.environ.get("PADDLE_TPU_SERVE_JOURNAL")
        if journal is not None and not isinstance(journal, RequestJournal):
            journal = RequestJournal(
                journal, max_bytes=self.config.journal_max_bytes)
        self.journal = journal
        # graceful-shutdown state: the signal handler only flips the
        # flag; the decode loop performs the drain at a step boundary
        self._drain_signal = []         # appended by _on_drain_signal
        self._drain_signal_deadline = None
        self._prev_handlers = {}
        self._drain_state = "serving"   # serving | draining | drained
        self._drain_report = None
        self.steps = 0
        self._busy_s = 0.0
        self._tokens_out = 0
        self._evicted_seen = 0
        # device-resident padded block tables, keyed on the KV
        # allocator's mutation version + the slot occupancy: prefill
        # admission / eviction invalidates, steady-state decode steps
        # reuse — retiring the one per-step H2D transfer whose payload
        # almost never changes (io/prefetch.py is the shared h2d lane)
        self._tables_dev = None
        self._tables_key = None
        self._results = {}        # finished, not yet drained by run()
        self._results_limit = 4096
        self._h_request = _telemetry.histogram(
            "paddle_tpu_serve_request_seconds",
            "submit-to-finish latency per served request",
            buckets=_LATENCY_BUCKETS)
        self._h_ttft = _telemetry.histogram(
            "paddle_tpu_serve_ttft_seconds",
            "submit-to-first-token latency per served request",
            buckets=_LATENCY_BUCKETS)
        self._c_req = _telemetry.counter(
            "paddle_tpu_serve_requests_total",
            "requests leaving the engine, by outcome", ("outcome",))
        self._c_tok = _telemetry.counter(
            "paddle_tpu_serve_tokens_total", "generated tokens")
        self._c_steps = _telemetry.counter(
            "paddle_tpu_serve_steps_total",
            "decode-loop iterations, by batch kind", ("kind",))
        self._g_tps = _telemetry.gauge(
            "paddle_tpu_serve_tokens_per_sec",
            "generated tokens per busy second (cumulative)")
        self._h_tpot = _telemetry.histogram(
            "paddle_tpu_serve_tpot_seconds",
            "inter-token decode gap (time-per-output-token)",
            buckets=_TPOT_BUCKETS)
        self._g_oldest = _telemetry.gauge(
            "paddle_tpu_serve_oldest_queued_age_seconds",
            "age of the oldest still-queued request (wedge signal)")
        # per-request lifecycle records: every exit path writes ONE
        # access record carrying the SAME measured latency/TTFT floats
        # the histograms observed, so tracing.reconcile_with_metrics
        # can check access-log aggregates against counters exactly
        self.access = AccessLog(
            self.config.access_log
            or os.environ.get("PADDLE_TPU_SERVE_ACCESS_LOG"),
            max_bytes=self.config.access_log_max_bytes)
        self.windows = ServingWindows()
        self._trace_slow_s = (
            self.config.trace_slow_s if self.config.trace_slow_s is not None
            else _env_float("PADDLE_TPU_SERVE_TRACE_SLOW_S", 2.0))
        self._slo_ttft_s = (
            self.config.slo_ttft_s if self.config.slo_ttft_s is not None
            else _env_float("PADDLE_TPU_SERVE_SLO_TTFT_S", 1.0))
        self._slo = SLOMonitor("serve_ttft",
                               objective=self.config.slo_objective)
        self._publish_every_s = 0.25
        self._last_publish_t = 0.0
        # crash-and-hang observability: the /serving statusz route and
        # postmortem bundles report this engine's scheduler + KV-pool
        # state (weak registration — the engine's lifetime is its own),
        # and a server process with PADDLE_TPU_DIAGNOSTICS_DIR set arms
        # bundles-on-fatal-signal for its decode loop
        _diagnostics.register_serving_engine(self)
        _diagnostics.ensure_installed()

    # -- request API --------------------------------------------------------

    def submit(self, prompt, max_new_tokens=16, deadline_s=None,
               eos_id=None, request_id=None, _resume=None):
        """Queue one request; returns its id. Raises `OverloadedError`
        (after counting an ``overloaded`` outcome) when admission
        control sheds it — the caller owns retry/backoff. Thread-safe:
        any thread may submit while the decode loop runs."""
        req = ServeRequest(prompt, max_new_tokens=max_new_tokens,
                           deadline_s=deadline_s, eos_id=eos_id,
                           request_id=request_id)
        if _resume:
            # recovery re-admission: `prompt` already carries the
            # previous life's tokens; remember the split so results
            # and the journal reconstruct the original request
            req.resume_prefix = [int(t) for t in _resume]
        self.windows.count_submitted()
        try:
            self.scheduler.submit(req)
        except OverloadedError as exc:
            self._c_req.labels(outcome="overloaded").inc()
            self.windows.count_shed()
            self._slo.observe(False)
            req.evict_reason = getattr(exc, "reason", None)
            # shed at the door: outcome counter incremented but the
            # request never entered paddle_tpu_serve_request_seconds,
            # so the access aggregate must not claim a latency either
            self._finish_request(req, "overloaded", None)
            raise
        if self.journal is not None:
            self.journal.record_submit(req)
        return req.request_id

    def cancel(self, request_id):
        """Abort a queued or running request NOW — its KV blocks are
        freed immediately and the outcome counter records
        ``cancelled``. Returns False for unknown/finished ids."""
        ok = self.scheduler.cancel(request_id)
        if ok:
            # the scheduler parked it in the evicted history; account
            # it (outcome label, latency histogram, journal fin) now
            # instead of waiting for the next step
            self._account_evicted()
        return ok

    def step(self):
        """One decode-loop iteration. Returns False when no work ran
        (idle queue and no running requests)."""
        from ..core.autograd import apply, no_grad
        from ..core.tensor import Tensor

        t0 = time.perf_counter()
        fault_point("serve.step", step=self.steps)
        plan = self.scheduler.plan(now=t0)
        if plan.n_rows == 0:
            # deadline sweeps may still have evicted queued requests
            self._account_evicted()
            return False
        with _tracing.span("serve_step", "serve", rows=plan.n_rows,
                           decode=plan.decode_rows,
                           prefill=plan.prefill_rows):
            tables = self._device_tables()
            # the step's ragged inputs go through the shared h2d lane
            # (histogram + io/h2d span from one measurement), same as
            # the training prefetcher's commits
            tok_a, rreq_a, rpos_a = _prefetch.commit_arrays(
                [plan.token_ids, plan.row_req, plan.row_pos],
                kind="serve_step")
            tok = Tensor(tok_a)
            rreq = Tensor(rreq_a)
            rpos = Tensor(rpos_a)
            with no_grad():
                logits = self.model.forward(
                    tok, rreq, rpos, self.cache, tables,
                    decode_only=plan.decode_only)
                sampled = apply(_greedy_sample, logits)
            # THE step sync: one host read of the sampled tokens (under
            # fusion, the step's single flush site)
            tokens = np.asarray(sampled._value)  # fuselint: ok[FL001] the decode loop's one intended per-step sync
        now = time.perf_counter()
        finished = self.scheduler.complete_step(plan, tokens, now=now)
        if self.journal is not None:
            # exactly the tokens complete_step appended (an eviction
            # that raced the batch contributes no journal token)
            self.journal.record_step(
                [(req.request_id, req.generated[-1])
                 for _row, req in plan.emit
                 if req.state != RequestState.EVICTED and req.generated])
        self.steps += 1
        self._busy_s += now - t0
        self._tokens_out += len(plan.emit)
        self._c_tok.inc(len(plan.emit))
        self._c_steps.labels(
            kind="decode" if plan.decode_only else "mixed").inc()
        # inter-token gaps measured ONCE by complete_step (same floats
        # feed the per-request aggregates in the access record); the
        # engine observes them back-to-back on the same decode thread
        for gap in self.scheduler.last_step_tpots:
            self._h_tpot.observe(gap)
        for _row, req in plan.emit:
            if req.t_first_token is not None and len(req.generated) == 1:
                dt = req.t_first_token - req.t_submit
                self._h_ttft.observe(dt)
                self.windows.observe_ttft(dt)
                _tracing.emit_span("ttft", "serve", req.t_submit_wall,
                                   dt, request=req.request_id)
        for req in finished:
            dt = req.t_done - req.t_submit
            self._h_request.observe(dt)
            _tracing.emit_span("request", "serve", req.t_submit_wall, dt,
                               request=req.request_id,
                               tokens=len(req.generated))
            self._c_req.labels(outcome="completed").inc()
            self.windows.count_tokens(len(req.generated))
            ttft = (req.t_first_token - req.t_submit
                    if req.t_first_token is not None else None)
            self._slo.observe(ttft is None or ttft <= self._slo_ttft_s)
            self._finish_request(req, "completed", dt)
            # full output = tokens from a previous process life (journal
            # recovery) + this life's generation
            out = req.resume_prefix + req.generated
            if self.journal is not None:
                self.journal.record_finish(req.request_id, "completed",
                                           tokens=out)
            # results parked until the next run() drains them (bounded
            # like the scheduler history — a step()-loop caller that
            # never drains must not grow memory per request served)
            self._results[req.request_id] = out
            while len(self._results) > self._results_limit:
                self._results.pop(next(iter(self._results)))
        self._account_evicted()
        self.windows.observe_queue_depth(len(self.scheduler.queue))
        self._publish_windows()
        if self._busy_s > 0:
            self._g_tps.set(self._tokens_out / self._busy_s)
        if self.elastic is not None:
            try:
                self.elastic.tick(self.steps)
            except Exception:  # noqa: BLE001 — liveness must not kill serving
                pass
        return True

    def _device_tables(self):
        """The padded block-table matrix, committed once per
        (allocation version, slot occupancy) — admission, growth, and
        eviction invalidate; pure decode steps reuse the device copy
        instead of re-transferring an identical matrix every step."""
        from ..core.tensor import Tensor

        running = self.scheduler.running
        ids = tuple(running[s].request_id if s in running else None
                    for s in range(self.config.max_running))
        key = (self.cache.alloc_version(), ids)
        if self._tables_dev is None or key != self._tables_key:
            arr = self.cache.padded_tables(list(ids))
            self._tables_dev = Tensor(
                _prefetch.commit_arrays([arr], kind="serve_tables")[0])
            self._tables_key = key
        return self._tables_dev

    def _account_evicted(self):
        # the scheduler's evicted deque is bounded; count by total and
        # read the newest entries (per-step evictions are far below the
        # history bound, so none rotate out before this runs)
        new = self.scheduler.evicted_total - self._evicted_seen
        if new <= 0:
            return
        self._evicted_seen = self.scheduler.evicted_total
        for req in list(self.scheduler.evicted)[-new:]:
            # reason -> outcome: a caller-initiated cancel and a queued-
            # too-long shed are not degradation-"evicted"; everything
            # else (deadline, kv_exhausted, prompt_too_long, drain) is
            outcome = {"cancelled": "cancelled",
                       "queue_timeout": "overloaded"}.get(
                           req.evict_reason, "evicted")
            self._c_req.labels(outcome=outcome).inc()
            if outcome == "overloaded":
                self.windows.count_shed()
            self._slo.observe(False)
            if self.journal is not None:
                self.journal.record_finish(req.request_id, outcome)
            # an evicted request still closes its latency span — the
            # operator's histogram covers every request that LEFT, not
            # only the happy path (outcome label tells them apart)
            dt = time.perf_counter() - req.t_submit
            self._h_request.observe(dt)
            _tracing.emit_span("request", "serve", req.t_submit_wall, dt,
                               request=req.request_id, evicted=True,
                               reason=req.evict_reason)
            self._finish_request(req, outcome, dt)

    # -- request-scoped observability (ISSUE 20) ----------------------------

    def _finish_request(self, req, outcome, latency_s):
        """Write the request's access record at exit. `latency_s` is the
        SAME float the request-latency histogram observed (None for a
        submit-time shed, which never entered that histogram), so
        access-log aggregates reconcile exactly with the metrics.
        Tail sampling: non-completed or slow requests additionally emit
        nested `serve/request/*` detail spans and a ``serve_access``
        event; the happy path keeps only the summary record."""
        ttft = (req.t_first_token - req.t_submit
                if req.t_first_token is not None else None)
        sampled = tail_sampled(outcome, latency_s, self._trace_slow_s)
        rec = {"kind": "serve_access",
               "request_id": req.request_id,
               "ts": round(time.time(), 6),
               "t_submit_wall": round(req.t_submit_wall, 6),
               "outcome": outcome,
               "latency_s": (round(latency_s, 6)
                             if latency_s is not None else None),
               "ttft_s": round(ttft, 6) if ttft is not None else None,
               "queue_wait_s": (round(req.t_scheduled - req.t_submit, 6)
                                if req.t_scheduled is not None else None),
               "prompt_len": len(req.prompt),
               "tokens_out": len(req.generated),
               "max_new_tokens": req.max_new_tokens,
               "deadline_s": req.deadline_s,
               "prefill_chunks": len(req.prefill_marks),
               "preemptions": req.preemptions,
               "tpot_count": req.tpot_count,
               "tpot_mean_s": (round(req.tpot_sum / req.tpot_count, 6)
                               if req.tpot_count else None),
               "tpot_max_s": (round(req.tpot_max, 6)
                              if req.tpot_count else None),
               "evict_reason": req.evict_reason,
               "sampled": sampled}
        if sampled:
            rec["prefill_marks"] = list(req.prefill_marks)
            rec["preempt_marks"] = list(req.preempt_marks)
            self._emit_detail_spans(req, outcome, latency_s, ttft)
            _telemetry.emit("serve_access",
                            **{k: v for k, v in rec.items()
                               if k != "kind"})
        self.access.record(rec, latency_s=latency_s, ttft_s=ttft)

    def _emit_detail_spans(self, req, outcome, latency_s, ttft):
        # nested timeline for sampled requests only; names are
        # "request/<phase>" so reconcile's EXACT-name span matching
        # keeps them out of the per-request `serve/request` checks
        total = latency_s if latency_s is not None else 0.0
        base = req.t_submit_wall
        q_end = (req.t_scheduled - req.t_submit
                 if req.t_scheduled is not None else total)
        q_end = max(0.0, min(q_end, total))
        _tracing.emit_span("request/queue", "serve", base, q_end,
                           request=req.request_id, outcome=outcome)
        if req.t_scheduled is not None:
            pf_end = ttft if ttft is not None else total
            pf_end = max(q_end, min(pf_end, total))
            _tracing.emit_span("request/prefill", "serve", base + q_end,
                               pf_end - q_end, request=req.request_id,
                               chunks=len(req.prefill_marks),
                               preemptions=req.preemptions)
        if ttft is not None:
            _tracing.emit_span("request/decode", "serve", base + ttft,
                               max(0.0, total - ttft),
                               request=req.request_id,
                               tokens=len(req.generated),
                               tpot_count=req.tpot_count)

    def _publish_windows(self, force=False):
        """Throttled export of the rolling windows: windowed gauges,
        the oldest-queued-age wedge gauge, and the SLO burn-rate
        evaluation (which emits ``slo_burn`` events when both windows
        burn). Called per step; cheap no-op inside the throttle."""
        nowm = time.monotonic()
        if not force and nowm - self._last_publish_t < self._publish_every_s:
            return None
        self._last_publish_t = nowm
        snap = self.windows.publish()
        oldest = self.scheduler.oldest_queued_age()
        self._g_oldest.set(oldest)
        panel = self._slo.evaluate()
        return {"windows": snap, "slo": panel,
                "oldest_queued_age_s": round(oldest, 6)}

    def slo_panel(self):
        """Fresh windows + SLO + oldest-queued-age panel (statusz)."""
        return self._publish_windows(force=True)

    def requestz_snapshot(self, recent=50):
        """The /requestz payload: every in-flight request with its age
        and phase, the ring of recent access records, and the windowed
        SLO panel. Safe from any thread: scheduler containers are
        copied first (C-level atomics), requests are read-only here."""
        now = time.perf_counter()
        queued = list(self.scheduler.queue)
        running = dict(self.scheduler.running)
        in_flight = []
        for req in queued:
            in_flight.append({
                "request_id": req.request_id, "phase": "queued",
                "age_s": round(now - req.t_submit, 6),
                "prompt_len": len(req.prompt),
                "max_new_tokens": req.max_new_tokens,
                "preemptions": req.preemptions})
        for slot, req in sorted(running.items()):
            in_flight.append({
                "request_id": req.request_id,
                "phase": ("prefill" if req.n_fed < len(req.prompt)
                          else "decode"),
                "slot": slot,
                "age_s": round(now - req.t_submit, 6),
                "prompt_len": len(req.prompt),
                "n_fed": req.n_fed,
                "generated": len(req.generated),
                "max_new_tokens": req.max_new_tokens,
                "preemptions": req.preemptions})
        panel = self.slo_panel()
        return {"in_flight": in_flight,
                "recent": self.access.recent(recent),
                "windows": panel["windows"],
                "slo": panel["slo"],
                "oldest_queued_age_s": panel["oldest_queued_age_s"],
                "access": self.access.stats()}

    def run(self, max_steps=None):
        """Drive `step()` until the queue drains (or `max_steps`).
        Returns {request_id: generated token list} for every request
        that finished since the previous `run()` call drained them."""
        limit = max_steps if max_steps is not None else self.config.max_steps
        steps = 0
        idle = 0
        while self.scheduler.has_work() and steps < limit:
            if self._drain_signal:
                self._handle_signal_drain()
                break
            if not self.step():
                if not self.scheduler.has_work():
                    break
                # work is queued but nothing was runnable (KV starved,
                # chaos-injected allocator failures, ...): return
                # promptly instead of spinning empty plans to max_steps
                idle += 1
                if idle >= 2:
                    break
            else:
                idle = 0
            steps += 1
        out, self._results = self._results, {}
        return out

    # -- graceful shutdown --------------------------------------------------

    def drain(self, deadline_s=None):
        """Stop admission, finish accepted work, evict stragglers at
        the deadline. Returns a report dict whose ``results`` carries
        everything that finished (including work completed by earlier
        steps but not yet drained by `run()`). Safe to call twice —
        the second call just sweeps what is left."""
        deadline = (self.config.drain_deadline_s
                    if deadline_s is None else float(deadline_s))
        t0 = time.perf_counter()
        st = self.scheduler.stats()
        self._drain_state = "draining"
        _telemetry.emit("serve_drain", state="begin", queued=st["queued"],
                        running=st["running"], deadline_s=deadline)
        self.scheduler.begin_drain()
        idle = 0
        while (self.scheduler.has_work()
               and time.perf_counter() - t0 < deadline):
            if not self.step():
                idle += 1
                if idle >= 2:
                    break  # leftovers are not runnable; evict them below
            else:
                idle = 0
        shed = 0
        if self.scheduler.has_work():
            shed = self.scheduler.shed_remaining("drain_deadline")
            self._account_evicted()
        results, self._results = self._results, {}
        dt = time.perf_counter() - t0
        self._drain_state = "drained"
        report = {"duration_s": dt, "completed": len(results),
                  "shed": shed, "results": results}
        self._drain_report = {"duration_s": round(dt, 6),
                              "completed": len(results), "shed": shed}
        _telemetry.emit("serve_drain", state="end",
                        completed=len(results), shed=shed,
                        duration_s=round(dt, 6))
        return report

    def install_signal_drain(self, signum=_signal.SIGTERM,
                             deadline_s=None):
        """Arm graceful drain on `signum` (main thread only — Python's
        signal contract). The handler only flips a flag; `run()` drains
        at the next step boundary, dumps a PR-14 postmortem bundle with
        the drain report, then re-delivers the signal through the
        previously-installed handler chain — a supervisor still sees
        the default termination semantics (rc ``-SIGTERM``) AND the
        diagnostics bundle still lands."""
        self._drain_signal_deadline = deadline_s
        self._prev_handlers[signum] = _signal.signal(
            signum, self._on_drain_signal)

    def _on_drain_signal(self, signum, frame):
        # flag only: a signal handler must not run the decode loop
        self._drain_signal.append(signum)

    def _handle_signal_drain(self):
        signum = self._drain_signal[-1]
        self.drain(self._drain_signal_deadline)
        _diagnostics.maybe_dump("sigterm_drain",
                                extra={"drain": self._drain_report})
        # chain: restore whatever was installed before us and
        # re-deliver, so the PR-14 fatal-signal bundle path and the
        # default rc=-signum semantics both still hold
        prev = self._prev_handlers.get(signum)
        _signal.signal(signum,
                       prev if callable(prev) else _signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    def generate(self, prompts, max_new_tokens=16, **kw):
        """Convenience: submit `prompts` (list of token lists), run to
        completion, return generated tokens in submission order."""
        ids = [self.submit(p, max_new_tokens=max_new_tokens, **kw)
               for p in prompts]
        out = self.run()
        return [out.get(i) for i in ids]

    # -- warm start ---------------------------------------------------------

    def warm_start(self, manifest_path=None):
        """AOT-precompile the shape manifest (path, or the
        ``PADDLE_TPU_SHAPE_MANIFEST`` env default) so a restarted server
        process performs zero fresh XLA compiles. Returns the precompile
        stats dict."""
        from ..runtime import warmup as _warmup

        doc = _warmup.load_manifest(manifest_path)
        return _warmup.precompile(doc)

    # -- crash recovery -----------------------------------------------------

    def recover(self, journal_path=None):
        """Re-admit a crashed process's unfinished journaled requests.

        Each unfinished request is resubmitted under its ORIGINAL id
        with the already-emitted tokens appended to its prompt (added
        context) and its token budget reduced by what was already
        generated — greedy sampling plus per-row ragged-batch
        independence make the resumed completion token-exact vs an
        uninterrupted run. A request whose journaled tokens already
        satisfy its stopping rule is returned as completed without
        re-admission. Deadlines restart at recovery (pre-crash queue
        time is not billed to the request).

        Returns ``{"resumed": [ids], "completed": {id: tokens},
        "skipped": [ids]}`` — ``completed`` holds pre-crash finishes
        plus already-done resumes; ``skipped`` holds requests shed by
        admission control on re-admission."""
        path = journal_path or (self.journal.path
                                if self.journal is not None else None)
        if path is None:
            raise ValueError("recover() needs a journal (engine journal "
                             "or explicit journal_path)")
        doc = read_journal(path)
        completed = dict(doc["completed"])
        resumed, skipped = [], []
        for spec in doc["unfinished"]:
            gen = spec["gen"]
            max_new = spec["max_new_tokens"]
            eos = spec["eos_id"]
            if ((max_new and len(gen) >= max_new)
                    or (eos is not None and gen and gen[-1] == eos)):
                # the crash lost only the fin record, not tokens
                completed[spec["id"]] = list(gen)
                if self.journal is not None:
                    self.journal.record_finish(spec["id"], "completed",
                                               tokens=gen)
                continue
            try:
                self.submit(spec["prompt"] + gen,
                            max_new_tokens=(max_new - len(gen)
                                            if max_new else 0),
                            deadline_s=spec["deadline_s"],
                            eos_id=eos, request_id=spec["id"],
                            _resume=gen)
                resumed.append(spec["id"])
            except OverloadedError:
                skipped.append(spec["id"])
        _telemetry.emit("serve_recover", path=path,
                        resumed=len(resumed), completed=len(completed),
                        skipped=len(skipped))
        return {"resumed": resumed, "completed": completed,
                "skipped": skipped}

    def stats(self):
        s = self.scheduler.stats()
        s.update(steps=self.steps, busy_s=self._busy_s,
                 tokens_out=self._tokens_out,
                 tokens_per_sec=(self._tokens_out / self._busy_s
                                 if self._busy_s else 0.0))
        return s

    def diagnostics_snapshot(self):
        """Engine + scheduler + KV-pool state for the diagnostics layer
        (the /serving statusz route and postmortem bundles): live
        request ids with their progress, pool occupancy, and the
        engine-level throughput counters — enough to see WHAT a wedged
        or dying server was doing, without touching device state."""
        # called from the statusz/watchdog threads while the engine
        # thread mutates scheduler state: copy the dict FIRST (a C-level
        # atomic) so iteration can never race an admit/evict resize
        running = dict(self.scheduler.running)
        return {
            "config": {"max_running": self.config.max_running,
                       "token_budget": self.config.token_budget,
                       "block_size": self.config.block_size,
                       "num_blocks": self.config.num_blocks},
            "stats": self.stats(),
            "kv": {"blocks_free": self.cache.blocks_free(),
                   "blocks_in_use": self.cache.blocks_in_use(),
                   "utilization": self.cache.utilization()},
            "running": [
                {"request_id": req.request_id, "slot": slot,
                 "prompt_len": len(req.prompt),
                 "generated": len(req.generated),
                 "max_new_tokens": req.max_new_tokens}
                for slot, req in sorted(running.items())],
            "queued": len(self.scheduler.queue),
            "queue": {"depth": len(self.scheduler.queue),
                      "max_queued": self.scheduler.max_queued,
                      "queued_blocks": self.scheduler.queued_blocks(),
                      "max_queued_blocks": self.scheduler.max_queued_blocks,
                      "max_queued_tokens": self.scheduler.max_queued_tokens,
                      "max_queue_wait_s": self.scheduler.max_queue_wait_s},
            "shed": {"total": self.scheduler.shed_total,
                     "by_reason": dict(self.scheduler.shed_by_reason)},
            "drain": {"state": self._drain_state,
                      "report": self._drain_report},
            "journal": (self.journal.stats()
                        if self.journal is not None else None),
            "undrained_results": len(self._results),
            "observability": {"windows": self.windows.snapshot(),
                              "slo": self._slo.evaluate(),
                              "access": self.access.stats()},
        }


def _greedy_sample(lg):
    import jax.numpy as jnp

    return jnp.argmax(lg, axis=-1).astype(jnp.int32)


_greedy_sample.__name__ = "serve_greedy_sample"  # dispatch/AMP key name
