"""Continuous-batching scheduler (ROADMAP item 1).

Per decode iteration the scheduler assembles one ragged batch under a
fixed token budget: every RUNNING request past its prefill contributes
exactly one decode row; leftover budget is fed to admitted requests'
unfed prompt tokens as chunked prefill. Requests are admitted the
moment a running slot AND at least one KV block are free, and evicted
the moment they finish, exhaust their deadline, or must be preempted to
un-wedge a decode that cannot grow its context (preemption returns the
youngest prefilling request to the queue and frees its blocks — the
victim restarts from scratch later; a decode-phase request is never
preempted for a prefill one).

Deadlines ride the resilience substrate: an expired request records a
``request_deadline`` fault event and is evicted AT the deadline check
of the next step — the batch loop keeps serving everyone else (the
FaultInjector acceptance test wedges a step with an injected delay and
proves the loop degrades per-request instead of globally).

All array outputs are fixed-shape (token budget T, slot count R, table
width Bmax) so the jit cache sees ONE step signature regardless of the
ragged mix — the padding-free property is about never paying a
[batch, max_seq] rectangle, not about varying T.

Overload contract (ISSUE 18): the waiting queue is BOUNDED — by count
(`max_queued`), by queued prompt tokens (`max_queued_tokens`, measured
in steps of token-budget backlog), and by the KV blocks the queued work
will need at full context (`max_queued_blocks`). `submit()` refuses
over-bound work with `OverloadedError` (the request is never queued, so
memory cannot grow with arrival rate), and a queued request that waits
past `max_queue_wait_s` is shed at the next `plan()` — both paths count
``serve_sheds`` fault events. `begin_drain()` flips admission off for a
graceful shutdown while accepted work finishes.

Thread contract: one RLock guards queue/running/accounting state, so
`submit()`/`cancel()` from any caller thread may race the decode
thread's `plan()`/`complete_step()`. Fault events observed under the
lock are DEFERRED and recorded after release — telemetry reaches the
event stream (file I/O) and must never run under the planner lock.
"""
from __future__ import annotations

import collections
import itertools
import threading
import time

import numpy as np

from ..runtime.resilience import record_fault

__all__ = ["RequestState", "ServeRequest", "StepPlan",
           "ContinuousBatchingScheduler", "OverloadedError"]


class OverloadedError(RuntimeError):
    """`submit()` refused a request: the engine is shedding load.

    `reason` is one of ``queue_full`` / ``token_backlog`` /
    ``kv_backlog`` / ``draining``. The request was never queued — the
    caller owns retry/backoff policy."""

    def __init__(self, request_id, reason):
        super().__init__(f"{request_id} shed: {reason}")
        self.request_id = request_id
        self.reason = reason


class RequestState:
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED = "finished"
    EVICTED = "evicted"


_ids = itertools.count()

# phase-mark lists are bounded: a pathological request (thousands of
# prefill chunks / preemptions) must not grow memory per event — counts
# keep counting, the timeline keeps its head
_MARK_LIMIT = 64


class ServeRequest:
    """One generation request. `deadline_s` is a wall-clock budget from
    submit; None = no deadline. `prompt` must be non-empty."""

    __slots__ = ("request_id", "prompt", "max_new_tokens", "deadline_s",
                 "eos_id", "state", "generated", "slot", "n_fed",
                 "n_cached", "t_submit", "t_submit_wall", "t_first_token",
                 "t_done", "preemptions", "evict_reason", "resume_prefix",
                 "t_scheduled", "prefill_marks", "preempt_marks",
                 "t_last_token", "tpot_sum", "tpot_max", "tpot_count")

    def __init__(self, prompt, max_new_tokens=16, deadline_s=None,
                 eos_id=None, request_id=None):
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        self.request_id = (request_id if request_id is not None
                           else f"req-{next(_ids)}")
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.deadline_s = deadline_s
        self.eos_id = eos_id
        self.state = RequestState.WAITING
        self.generated = []
        self.slot = None          # running-slot index while RUNNING
        self.n_fed = 0            # prompt tokens scheduled into batches
        self.n_cached = 0         # context positions present in the cache
        self.t_submit = time.perf_counter()
        self.t_submit_wall = time.time()
        self.t_first_token = None
        self.t_done = None
        self.preemptions = 0
        self.evict_reason = None
        # journal recovery: tokens this request already generated in a
        # previous process life (its scheduling `prompt` then carries
        # them as context; final output = resume_prefix + generated)
        self.resume_prefix = []
        # phase timeline (ISSUE 20): first time this request entered a
        # batch, bounded (offset_s, chunk) prefill marks, bounded
        # preemption offsets, and per-token decode (TPOT) aggregates —
        # the engine folds these into the request's access record
        self.t_scheduled = None
        self.prefill_marks = []
        self.preempt_marks = []
        self.t_last_token = None
        self.tpot_sum = 0.0
        self.tpot_max = 0.0
        self.tpot_count = 0

    @property
    def context_len(self):
        """Positions the NEXT scheduled token would extend to."""
        return self.n_cached

    def expired(self, now):
        return (self.deadline_s is not None
                and now - self.t_submit > self.deadline_s)

    def __repr__(self):
        return (f"ServeRequest({self.request_id}, {self.state}, "
                f"fed={self.n_fed}/{len(self.prompt)}, "
                f"gen={len(self.generated)}/{self.max_new_tokens})")


class StepPlan:
    """One ragged batch: fixed-shape i32 arrays + the emit map."""

    __slots__ = ("token_ids", "row_req", "row_pos", "emit", "n_rows",
                 "decode_rows", "prefill_rows", "scheduled")

    def __init__(self, token_budget):
        self.token_ids = np.zeros(token_budget, np.int32)
        self.row_req = np.zeros(token_budget, np.int32)
        self.row_pos = np.full(token_budget, -1, np.int32)
        self.emit = []            # (row index, ServeRequest)
        self.n_rows = 0
        self.decode_rows = 0
        self.prefill_rows = 0
        self.scheduled = []

    @property
    def decode_only(self):
        return self.n_rows > 0 and self.prefill_rows == 0

    def add_row(self, token, slot, pos, request, emits):
        i = self.n_rows
        self.token_ids[i] = token
        self.row_req[i] = slot
        self.row_pos[i] = pos
        if emits:
            self.emit.append((i, request))
        self.n_rows += 1


class ContinuousBatchingScheduler:
    """Admission queue + running set over a PagedKVCache."""

    def __init__(self, cache, max_running=4, token_budget=16,
                 default_deadline_s=None, history_limit=1024,
                 max_queued=256, max_queued_tokens=None,
                 max_queued_blocks=None, max_queue_wait_s=None):
        if token_budget < 1 or max_running < 1:
            raise ValueError("token_budget and max_running must be >= 1")
        self.cache = cache
        self.max_running = int(max_running)
        self.token_budget = int(token_budget)
        self.default_deadline_s = default_deadline_s
        self.queue = collections.deque()
        self.running = {}         # slot -> ServeRequest
        # bounded retrospection only — a long-running server must not
        # retain every request ever served; totals keep counting
        self.finished = collections.deque(maxlen=int(history_limit))
        self.evicted = collections.deque(maxlen=int(history_limit))
        self.finished_total = 0
        self.evicted_total = 0
        self._admit_order = itertools.count()
        self._admitted_at = {}    # request_id -> admit sequence number
        # -- admission bounds (None picks a default scaled to the
        # engine's actual capacity, so defaults degrade sanely when the
        # pool/budget shrink) --
        self.max_queued = int(max_queued)
        self.max_queued_tokens = (int(max_queued_tokens)
                                  if max_queued_tokens is not None
                                  else 64 * self.token_budget)
        self.max_queued_blocks = (int(max_queued_blocks)
                                  if max_queued_blocks is not None
                                  else 4 * cache.config.num_blocks)
        self.max_queue_wait_s = max_queue_wait_s
        self.draining = False
        self.shed_total = 0
        self.shed_by_reason = {}
        # one lock for queue/running/accounting; fault events observed
        # under it are parked here and recorded after release
        self._lock = threading.RLock()
        self._deferred = collections.deque()
        # the most recent complete_step's inter-token gaps (decode
        # thread writes, engine reads back-to-back on the same thread)
        self.last_step_tpots = []

    # -- lifecycle ----------------------------------------------------------

    def submit(self, request):
        """Admit `request` to the bounded waiting queue, or shed it
        with `OverloadedError` (never queued; memory cannot grow with
        arrival rate). Thread-safe against the decode thread's
        `plan()`/`complete_step()`."""
        with self._lock:
            if request.deadline_s is None:
                request.deadline_s = self.default_deadline_s
            reason = self._shed_reason(request)
            if reason is None:
                self.queue.append(request)
            else:
                request.state = RequestState.EVICTED
                request.evict_reason = reason
                self._count_shed(reason)
        if reason is not None:
            # outside the lock: fault recording reaches the telemetry
            # event stream (file I/O must not serialize the planner)
            record_fault("serve_sheds", f"{request.request_id}: {reason}")
            raise OverloadedError(request.request_id, reason)
        return request.request_id

    def _shed_reason(self, request):
        """First violated admission bound, or None to admit."""
        if self.draining:
            return "draining"
        if len(self.queue) >= self.max_queued:
            return "queue_full"
        if (sum(len(r.prompt) for r in self.queue) + len(request.prompt)
                > self.max_queued_tokens):
            return "token_backlog"
        if (self.queued_blocks() + self._blocks_needed(request)
                > self.max_queued_blocks):
            return "kv_backlog"
        return None

    def _count_shed(self, reason):
        self.shed_total += 1
        self.shed_by_reason[reason] = self.shed_by_reason.get(reason, 0) + 1

    def _blocks_needed(self, req):
        """KV blocks `req` will need at its full context horizon."""
        horizon = min(len(req.prompt) + req.max_new_tokens + 1,
                      self.cache.config.max_context)
        return self.cache.blocks_for(horizon)

    def queued_blocks(self):
        """Blocks the whole waiting queue will eventually claim."""
        with self._lock:
            return sum(self._blocks_needed(r) for r in self.queue)

    def cancel(self, request_id):
        """Remove a queued or running request NOW, freeing its KV
        blocks immediately. Returns False for unknown/finished ids.
        No fault event — cancellation is caller intent, not
        degradation (the engine labels the outcome counter)."""
        with self._lock:
            for req in list(self.queue):
                if req.request_id == request_id:
                    self.queue.remove(req)
                    self._evict(req, "cancelled")
                    return True
            for req in list(self.running.values()):
                if req.request_id == request_id:
                    self._evict(req, "cancelled")
                    return True
        return False

    def begin_drain(self):
        """Stop admission (submit sheds with reason ``draining``);
        already-accepted work keeps running to completion."""
        with self._lock:
            self.draining = True

    def shed_remaining(self, reason="drain_deadline"):
        """Evict every queued and running request (the drain deadline
        expired). Returns how many were evicted."""
        n = 0
        with self._lock:
            while self.queue:
                self._evict(self.queue.popleft(), reason)
                n += 1
            for req in list(self.running.values()):
                self._evict(req, reason)
                n += 1
        return n

    def has_work(self):
        return bool(self.queue or self.running)

    def _free_slot(self):
        for s in range(self.max_running):
            if s not in self.running:
                return s
        return None

    def _evict(self, req, reason, fault=None):
        """Remove `req` from the running set and free its blocks.
        Caller holds the lock; the fault event (if any) is deferred to
        the next unlocked `_flush_faults()`."""
        self.cache.release(req.request_id)
        if req.slot is not None:
            self.running.pop(req.slot, None)
        req.slot = None
        req.state = RequestState.EVICTED
        req.evict_reason = reason
        self.evicted.append(req)
        self.evicted_total += 1
        self._admitted_at.pop(req.request_id, None)
        if fault:
            detail = f"{req.request_id}: {reason}"
            self._deferred.append(lambda: record_fault(fault, detail))

    def _flush_faults(self):
        """Record the fault events the locked sections deferred. Always
        called with the lock RELEASED (deque ops are atomic)."""
        while self._deferred:
            try:
                fn = self._deferred.popleft()
            except IndexError:
                return
            fn()

    def _ensure(self, request_id, num_tokens):
        """`cache.ensure_capacity` through the ``serve.kv_alloc`` fault
        point: an injected allocator failure degrades to "no capacity"
        (preempt / evict / wait — the decode loop's normal exhaustion
        paths) instead of crashing the loop."""
        try:
            return self.cache.ensure_capacity(request_id, num_tokens)
        except OSError:
            return False

    def _preempt_for_blocks(self, needy):
        """Free blocks for a decode request by returning the YOUNGEST
        still-prefilling request to the queue (it restarts later).
        Returns True if anything was preempted."""
        victims = [r for r in self.running.values()
                   if r is not needy and r.n_fed < len(r.prompt)]
        if not victims:
            return False
        victim = max(victims,
                     key=lambda r: self._admitted_at.get(r.request_id, 0))
        self.cache.release(victim.request_id)
        self.running.pop(victim.slot, None)
        victim.slot = None
        victim.state = RequestState.WAITING
        victim.n_fed = 0
        victim.n_cached = 0
        victim.preemptions += 1
        if len(victim.preempt_marks) < _MARK_LIMIT:
            victim.preempt_marks.append(
                round(time.perf_counter() - victim.t_submit, 6))
        self.queue.appendleft(victim)
        detail = (f"{victim.request_id} preempted for "
                  f"{needy.request_id}")
        self._deferred.append(lambda: record_fault("kv_preemptions", detail))
        return True

    # -- the per-iteration planner -----------------------------------------

    def plan(self, now=None):
        """Build the next ragged batch. Returns a StepPlan (possibly
        empty: nothing runnable this iteration)."""
        now = time.perf_counter() if now is None else now
        with self._lock:
            plan = self._plan_locked(now)
        self._flush_faults()
        return plan

    def _plan_locked(self, now):
        # 1. deadlines: expired requests leave the batch loop HERE, so a
        # slow request can never wedge the others past its budget; a
        # queued request past the max queue wait is shed the same way
        # (admitting work that already waited too long only burns KV on
        # a request whose caller has likely given up)
        for req in list(self.running.values()):
            if req.expired(now):
                self._evict(req, "deadline", fault="request_deadline")
        for req in list(self.queue):
            if req.expired(now):
                self.queue.remove(req)
                self._evict(req, "deadline_queued",
                            fault="request_deadline")
            elif (self.max_queue_wait_s is not None
                    and now - req.t_submit > self.max_queue_wait_s):
                self.queue.remove(req)
                self._count_shed("queue_timeout")
                self._evict(req, "queue_timeout", fault="serve_sheds")
        # 2. admission: slot free + at least one block to start on. A
        # prompt that cannot fit the per-request context bound even
        # with every generated token still to come is rejected HERE —
        # admitted, it would starve in the prefill loop forever
        while self.queue and self.cache.blocks_free() > 0:
            slot = self._free_slot()
            if slot is None:
                break
            req = self.queue.popleft()
            if len(req.prompt) + 1 > self.cache.config.max_context:
                self._evict(req, "prompt_too_long", fault="kv_evictions")
                continue
            req.slot = slot
            req.state = RequestState.RUNNING
            self.running[slot] = req
            self._admitted_at[req.request_id] = next(self._admit_order)
        plan = StepPlan(self.token_budget)
        budget = self.token_budget
        # 3. decode rows first: one token per request in decode phase.
        # Slot order keeps the batch layout deterministic.
        for slot in sorted(self.running):
            if budget <= 0:
                break
            req = self.running.get(slot)
            # a preemption for an earlier slot's decode may have removed
            # this one from the snapshot sorted() took
            if (req is None or req.n_fed < len(req.prompt)
                    or self._done(req)):
                continue
            if req.n_cached + 1 > self.cache.config.max_context:
                # the per-request block bound can NEVER be satisfied by
                # freeing peers' blocks — evict directly instead of
                # running a futile preemption cascade that would restart
                # every prefilling request for nothing
                self._evict(req, "context_exhausted", fault="kv_evictions")
                continue
            while not self._ensure(req.request_id, req.n_cached + 1):
                if not self._preempt_for_blocks(req):
                    break
            else:
                token = (req.generated[-1] if req.generated
                         else req.prompt[-1])
                if req.t_scheduled is None:
                    req.t_scheduled = now
                plan.add_row(token, slot, req.n_cached, req, emits=True)
                plan.decode_rows += 1
                plan.scheduled.append(req)
                req.n_cached += 1
                budget -= 1
                continue
            # capacity unobtainable even after preemption: the request
            # hit max_blocks_per_seq or the pool is truly exhausted
            self._evict(req, "kv_exhausted", fault="kv_evictions")
        # 4. prefill chunks fill the remaining budget, oldest admission
        # first (FIFO fairness; chunking keeps one request's long prompt
        # from starving the batch forever)
        for slot in sorted(
                self.running,
                key=lambda s: self._admitted_at.get(
                    self.running[s].request_id, 0)):
            if budget <= 0:
                break
            req = self.running.get(slot)
            if req is None or req.n_fed >= len(req.prompt):
                continue
            chunk = min(budget, len(req.prompt) - req.n_fed)
            while chunk > 0 and not self._ensure(
                    req.request_id, req.n_fed + chunk):
                # shrink to what the pool (and the per-request block
                # bound) can hold before resorting to waiting; always
                # strictly shrinks, so the loop terminates
                fit = min((self.cache.blocks_free()
                           + self.cache.blocks_for(req.n_cached))
                          * self.cache.config.block_size,
                          self.cache.config.max_context) - req.n_fed
                chunk = min(chunk - 1, max(0, fit))
            if chunk <= 0:
                continue
            if req.t_scheduled is None:
                req.t_scheduled = now
            if len(req.prefill_marks) < _MARK_LIMIT:
                req.prefill_marks.append(
                    (round(now - req.t_submit, 6), chunk))
            last = len(req.prompt) - 1
            for j in range(chunk):
                pos = req.n_fed + j
                plan.add_row(req.prompt[pos], slot, pos, req,
                             emits=pos == last)
            plan.prefill_rows += chunk
            plan.scheduled.append(req)
            req.n_fed += chunk
            req.n_cached = req.n_fed
            budget -= chunk
        return plan

    def _done(self, req):
        if req.max_new_tokens and len(req.generated) >= req.max_new_tokens:
            return True
        return (req.eos_id is not None and req.generated
                and req.generated[-1] == req.eos_id)

    def complete_step(self, plan, tokens, now=None):
        """Apply one step's sampled tokens (host ints, indexed by
        plan.emit rows). Returns the requests that finished this step."""
        now = time.perf_counter() if now is None else now
        done = []
        tpots = []
        with self._lock:
            for row, req in plan.emit:
                if req.state != RequestState.RUNNING:
                    continue  # evicted mid-step (deadline raced the batch)
                req.generated.append(int(tokens[row]))
                if req.t_first_token is None:
                    req.t_first_token = now
                else:
                    # inter-token (decode) gap — the TPOT sample. The
                    # request-level aggregates and the engine's TPOT
                    # histogram are fed from this SAME gap value, so
                    # access records reconcile with the histogram.
                    prev = (req.t_last_token
                            if req.t_last_token is not None
                            else req.t_first_token)
                    gap = max(0.0, now - prev)
                    req.tpot_sum += gap
                    req.tpot_count += 1
                    if gap > req.tpot_max:
                        req.tpot_max = gap
                    tpots.append(gap)
                req.t_last_token = now
                if self._done(req):
                    req.t_done = now
                    req.state = RequestState.FINISHED
                    self.cache.release(req.request_id)
                    self.running.pop(req.slot, None)
                    req.slot = None
                    self.finished.append(req)
                    self.finished_total += 1
                    self._admitted_at.pop(req.request_id, None)
                    done.append(req)
        # single-writer handoff: only the decode thread calls
        # complete_step, and the engine reads this immediately after —
        # the list is replaced wholesale, never mutated in place
        self.last_step_tpots = tpots
        return done

    def oldest_queued_age(self, now=None):
        """Seconds the longest-waiting QUEUED request has been waiting
        (0.0 when the queue is empty). This is the server-published
        wedge signal: a live engine drains its queue, so a growing
        oldest age — not wall-clock elapsed — is what distinguishes a
        wedged loop from a merely long run (tools/loadgen.py keys its
        ``wedged`` verdict on this instead of client-side inference)."""
        now = time.perf_counter() if now is None else now
        with self._lock:
            if not self.queue:
                return 0.0
            return max(0.0, now - min(r.t_submit for r in self.queue))

    def stats(self):
        with self._lock:
            return {"queued": len(self.queue),
                    "running": len(self.running),
                    "finished": self.finished_total,
                    "evicted": self.evicted_total,
                    "shed": self.shed_total,
                    "shed_by_reason": dict(self.shed_by_reason),
                    "draining": self.draining,
                    "queued_blocks": self.queued_blocks(),
                    "oldest_queued_age_s": self.oldest_queued_age(),
                    "kv": self.cache.stats()}
