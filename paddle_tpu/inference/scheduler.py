"""Continuous-batching scheduler (ROADMAP item 1).

Per decode iteration the scheduler assembles one ragged batch under a
fixed token budget: every RUNNING request past its prefill contributes
exactly one decode row; leftover budget is fed to admitted requests'
unfed prompt tokens as chunked prefill. Requests are admitted the
moment a running slot AND at least one KV block are free, and evicted
the moment they finish, exhaust their deadline, or must be preempted to
un-wedge a decode that cannot grow its context (preemption returns the
youngest prefilling request to the queue and frees its blocks — the
victim restarts from scratch later; a decode-phase request is never
preempted for a prefill one).

Deadlines ride the resilience substrate: an expired request records a
``request_deadline`` fault event and is evicted AT the deadline check
of the next step — the batch loop keeps serving everyone else (the
FaultInjector acceptance test wedges a step with an injected delay and
proves the loop degrades per-request instead of globally).

All array outputs are fixed-shape (token budget T, slot count R, table
width Bmax) so the jit cache sees ONE step signature regardless of the
ragged mix — the padding-free property is about never paying a
[batch, max_seq] rectangle, not about varying T.
"""
from __future__ import annotations

import collections
import itertools
import time

import numpy as np

from ..runtime.resilience import record_fault

__all__ = ["RequestState", "ServeRequest", "StepPlan",
           "ContinuousBatchingScheduler"]


class RequestState:
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED = "finished"
    EVICTED = "evicted"


_ids = itertools.count()


class ServeRequest:
    """One generation request. `deadline_s` is a wall-clock budget from
    submit; None = no deadline. `prompt` must be non-empty."""

    __slots__ = ("request_id", "prompt", "max_new_tokens", "deadline_s",
                 "eos_id", "state", "generated", "slot", "n_fed",
                 "n_cached", "t_submit", "t_submit_wall", "t_first_token",
                 "t_done", "preemptions", "evict_reason")

    def __init__(self, prompt, max_new_tokens=16, deadline_s=None,
                 eos_id=None, request_id=None):
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        self.request_id = (request_id if request_id is not None
                           else f"req-{next(_ids)}")
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.deadline_s = deadline_s
        self.eos_id = eos_id
        self.state = RequestState.WAITING
        self.generated = []
        self.slot = None          # running-slot index while RUNNING
        self.n_fed = 0            # prompt tokens scheduled into batches
        self.n_cached = 0         # context positions present in the cache
        self.t_submit = time.perf_counter()
        self.t_submit_wall = time.time()
        self.t_first_token = None
        self.t_done = None
        self.preemptions = 0
        self.evict_reason = None

    @property
    def context_len(self):
        """Positions the NEXT scheduled token would extend to."""
        return self.n_cached

    def expired(self, now):
        return (self.deadline_s is not None
                and now - self.t_submit > self.deadline_s)

    def __repr__(self):
        return (f"ServeRequest({self.request_id}, {self.state}, "
                f"fed={self.n_fed}/{len(self.prompt)}, "
                f"gen={len(self.generated)}/{self.max_new_tokens})")


class StepPlan:
    """One ragged batch: fixed-shape i32 arrays + the emit map."""

    __slots__ = ("token_ids", "row_req", "row_pos", "emit", "n_rows",
                 "decode_rows", "prefill_rows", "scheduled")

    def __init__(self, token_budget):
        self.token_ids = np.zeros(token_budget, np.int32)
        self.row_req = np.zeros(token_budget, np.int32)
        self.row_pos = np.full(token_budget, -1, np.int32)
        self.emit = []            # (row index, ServeRequest)
        self.n_rows = 0
        self.decode_rows = 0
        self.prefill_rows = 0
        self.scheduled = []

    @property
    def decode_only(self):
        return self.n_rows > 0 and self.prefill_rows == 0

    def add_row(self, token, slot, pos, request, emits):
        i = self.n_rows
        self.token_ids[i] = token
        self.row_req[i] = slot
        self.row_pos[i] = pos
        if emits:
            self.emit.append((i, request))
        self.n_rows += 1


class ContinuousBatchingScheduler:
    """Admission queue + running set over a PagedKVCache."""

    def __init__(self, cache, max_running=4, token_budget=16,
                 default_deadline_s=None, history_limit=1024):
        if token_budget < 1 or max_running < 1:
            raise ValueError("token_budget and max_running must be >= 1")
        self.cache = cache
        self.max_running = int(max_running)
        self.token_budget = int(token_budget)
        self.default_deadline_s = default_deadline_s
        self.queue = collections.deque()
        self.running = {}         # slot -> ServeRequest
        # bounded retrospection only — a long-running server must not
        # retain every request ever served; totals keep counting
        self.finished = collections.deque(maxlen=int(history_limit))
        self.evicted = collections.deque(maxlen=int(history_limit))
        self.finished_total = 0
        self.evicted_total = 0
        self._admit_order = itertools.count()
        self._admitted_at = {}    # request_id -> admit sequence number

    # -- lifecycle ----------------------------------------------------------

    def submit(self, request):
        if request.deadline_s is None:
            request.deadline_s = self.default_deadline_s
        self.queue.append(request)
        return request.request_id

    def has_work(self):
        return bool(self.queue or self.running)

    def _free_slot(self):
        for s in range(self.max_running):
            if s not in self.running:
                return s
        return None

    def _evict(self, req, reason, fault=None):
        """Remove `req` from the running set and free its blocks."""
        self.cache.release(req.request_id)
        if req.slot is not None:
            self.running.pop(req.slot, None)
        req.slot = None
        req.state = RequestState.EVICTED
        req.evict_reason = reason
        self.evicted.append(req)
        self.evicted_total += 1
        self._admitted_at.pop(req.request_id, None)
        if fault:
            record_fault(fault, f"{req.request_id}: {reason}")

    def _preempt_for_blocks(self, needy):
        """Free blocks for a decode request by returning the YOUNGEST
        still-prefilling request to the queue (it restarts later).
        Returns True if anything was preempted."""
        victims = [r for r in self.running.values()
                   if r is not needy and r.n_fed < len(r.prompt)]
        if not victims:
            return False
        victim = max(victims,
                     key=lambda r: self._admitted_at.get(r.request_id, 0))
        self.cache.release(victim.request_id)
        self.running.pop(victim.slot, None)
        victim.slot = None
        victim.state = RequestState.WAITING
        victim.n_fed = 0
        victim.n_cached = 0
        victim.preemptions += 1
        self.queue.appendleft(victim)
        record_fault("kv_preemptions",
                     f"{victim.request_id} preempted for "
                     f"{needy.request_id}")
        return True

    # -- the per-iteration planner -----------------------------------------

    def plan(self, now=None):
        """Build the next ragged batch. Returns a StepPlan (possibly
        empty: nothing runnable this iteration)."""
        now = time.perf_counter() if now is None else now
        # 1. deadlines: expired requests leave the batch loop HERE, so a
        # slow request can never wedge the others past its budget
        for req in list(self.running.values()):
            if req.expired(now):
                self._evict(req, "deadline", fault="request_deadline")
        for req in list(self.queue):
            if req.expired(now):
                self.queue.remove(req)
                self._evict(req, "deadline_queued",
                            fault="request_deadline")
        # 2. admission: slot free + at least one block to start on. A
        # prompt that cannot fit the per-request context bound even
        # with every generated token still to come is rejected HERE —
        # admitted, it would starve in the prefill loop forever
        while self.queue and self.cache.blocks_free() > 0:
            slot = self._free_slot()
            if slot is None:
                break
            req = self.queue.popleft()
            if len(req.prompt) + 1 > self.cache.config.max_context:
                self._evict(req, "prompt_too_long", fault="kv_evictions")
                continue
            req.slot = slot
            req.state = RequestState.RUNNING
            self.running[slot] = req
            self._admitted_at[req.request_id] = next(self._admit_order)
        plan = StepPlan(self.token_budget)
        budget = self.token_budget
        # 3. decode rows first: one token per request in decode phase.
        # Slot order keeps the batch layout deterministic.
        for slot in sorted(self.running):
            if budget <= 0:
                break
            req = self.running.get(slot)
            # a preemption for an earlier slot's decode may have removed
            # this one from the snapshot sorted() took
            if (req is None or req.n_fed < len(req.prompt)
                    or self._done(req)):
                continue
            if req.n_cached + 1 > self.cache.config.max_context:
                # the per-request block bound can NEVER be satisfied by
                # freeing peers' blocks — evict directly instead of
                # running a futile preemption cascade that would restart
                # every prefilling request for nothing
                self._evict(req, "context_exhausted", fault="kv_evictions")
                continue
            while not self.cache.ensure_capacity(req.request_id,
                                                 req.n_cached + 1):
                if not self._preempt_for_blocks(req):
                    break
            else:
                token = (req.generated[-1] if req.generated
                         else req.prompt[-1])
                plan.add_row(token, slot, req.n_cached, req, emits=True)
                plan.decode_rows += 1
                plan.scheduled.append(req)
                req.n_cached += 1
                budget -= 1
                continue
            # capacity unobtainable even after preemption: the request
            # hit max_blocks_per_seq or the pool is truly exhausted
            self._evict(req, "kv_exhausted", fault="kv_evictions")
        # 4. prefill chunks fill the remaining budget, oldest admission
        # first (FIFO fairness; chunking keeps one request's long prompt
        # from starving the batch forever)
        for slot in sorted(
                self.running,
                key=lambda s: self._admitted_at.get(
                    self.running[s].request_id, 0)):
            if budget <= 0:
                break
            req = self.running.get(slot)
            if req is None or req.n_fed >= len(req.prompt):
                continue
            chunk = min(budget, len(req.prompt) - req.n_fed)
            while chunk > 0 and not self.cache.ensure_capacity(
                    req.request_id, req.n_fed + chunk):
                # shrink to what the pool (and the per-request block
                # bound) can hold before resorting to waiting; always
                # strictly shrinks, so the loop terminates
                fit = min((self.cache.blocks_free()
                           + self.cache.blocks_for(req.n_cached))
                          * self.cache.config.block_size,
                          self.cache.config.max_context) - req.n_fed
                chunk = min(chunk - 1, max(0, fit))
            if chunk <= 0:
                continue
            last = len(req.prompt) - 1
            for j in range(chunk):
                pos = req.n_fed + j
                plan.add_row(req.prompt[pos], slot, pos, req,
                             emits=pos == last)
            plan.prefill_rows += chunk
            plan.scheduled.append(req)
            req.n_fed += chunk
            req.n_cached = req.n_fed
            budget -= chunk
        return plan

    def _done(self, req):
        if req.max_new_tokens and len(req.generated) >= req.max_new_tokens:
            return True
        return (req.eos_id is not None and req.generated
                and req.generated[-1] == req.eos_id)

    def complete_step(self, plan, tokens, now=None):
        """Apply one step's sampled tokens (host ints, indexed by
        plan.emit rows). Returns the requests that finished this step."""
        now = time.perf_counter() if now is None else now
        done = []
        for row, req in plan.emit:
            if req.state != RequestState.RUNNING:
                continue  # evicted mid-step (deadline raced the batch)
            req.generated.append(int(tokens[row]))
            if req.t_first_token is None:
                req.t_first_token = now
            if self._done(req):
                req.t_done = now
                req.state = RequestState.FINISHED
                self.cache.release(req.request_id)
                self.running.pop(req.slot, None)
                req.slot = None
                self.finished.append(req)
                self.finished_total += 1
                self._admitted_at.pop(req.request_id, None)
                done.append(req)
        return done

    def stats(self):
        return {"queued": len(self.queue),
                "running": len(self.running),
                "finished": self.finished_total,
                "evicted": self.evicted_total,
                "kv": self.cache.stats()}
