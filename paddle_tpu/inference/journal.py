"""Append-only serving request journal (ISSUE 18 crash recovery).

Every admitted request writes a ``sub`` record (original prompt,
sampling/stopping params), every decode step appends one ``tok`` record
carrying the step's emitted (request_id, token) pairs, and every
request that leaves the engine writes a ``fin`` record with its outcome
(completed fins carry the full token list). A process killed mid-decode
therefore leaves enough on disk to reconstruct, per request: what was
asked, and every token already emitted. `read_journal()` folds the file
back into that state; `ServingEngine.recover()` re-admits the
unfinished tail with the already-generated tokens as added context —
greedy sampling plus per-row batch independence make the resumed
completion token-exact vs an uninterrupted run.

Durability + liveness contract (the PR-14 spill idiom):

* appends are buffered line writes under a private lock, flushed per
  record — a SIGKILL loses at most the final partially-written line,
  which `read_journal` tolerates as a torn tail;
* when the file outgrows ``max_bytes`` it is COMPACTED, not rotated
  away: live (unfinished) requests are rewritten as fresh ``sub``
  records carrying their generated-so-far tokens into a tmp file that
  atomically `os.replace`s the journal — readers see the old file or
  the new one, never half of either. Finished records are dropped by
  compaction (results were already delivered at finish time);
* a write failure NEVER raises into the decode loop: the record is
  dropped, a ``journal_errors`` fault is counted, and serving
  continues journal-less-degraded. The ``serve.journal_write`` fault
  point makes that path testable.
"""
from __future__ import annotations

import json
import os
import threading

from ..runtime.resilience import fault_point, record_fault

__all__ = ["RequestJournal", "read_journal", "iter_jsonl"]


def iter_jsonl(path):
    """Yield parsed records from one JSONL file, skipping blank and
    unparseable lines — the torn-tail contract shared by the journal,
    the access log (access_log.py), and the telemetry event stream: a
    SIGKILL mid-write loses at most the line in flight, and a reader
    prefers a lost record to a wedged restart."""
    if not os.path.exists(path):
        return
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                continue  # torn tail from a crash — expected


class RequestJournal:
    """Append-only JSONL journal for one ServingEngine."""

    def __init__(self, path, max_bytes=4 << 20, fsync=False):
        self.path = os.path.abspath(str(path))
        self.max_bytes = int(max_bytes)
        self.fsync = bool(fsync)
        self._lock = threading.Lock()
        self._fh = None
        self._bytes = 0
        self._records = 0
        self._compactions = 0
        self.errors = 0
        # id -> {"prompt","max_new_tokens","eos_id","deadline_s","gen"}:
        # the live (unfinished) set, exactly what compaction rewrites.
        # Bounded by the scheduler's admission bounds, not by traffic.
        self._live = {}
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        try:
            self._fh = open(self.path, "a", encoding="utf-8")
            self._bytes = self._fh.tell()
        except OSError as e:
            self._note_error(e)

    # -- record producers (called from the engine) --------------------------

    def record_submit(self, req):
        """One admitted request. For a recovery re-admission the
        scheduling prompt carries the previous life's tokens — the
        record stores the ORIGINAL prompt plus those tokens as ``gen``
        so a second crash still reconstructs the original request."""
        prefix = list(req.resume_prefix)
        orig = (req.prompt[:len(req.prompt) - len(prefix)]
                if prefix else req.prompt)
        rec = {"k": "sub", "id": req.request_id, "prompt": list(orig),
               "max_new_tokens": int(req.max_new_tokens),
               "eos_id": req.eos_id, "deadline_s": req.deadline_s}
        if prefix:
            rec["gen"] = prefix
        with self._lock:
            self._live[req.request_id] = {
                "prompt": list(orig),
                "max_new_tokens": int(req.max_new_tokens),
                "eos_id": req.eos_id, "deadline_s": req.deadline_s,
                "gen": list(prefix)}
        self._append(rec)

    def record_step(self, pairs):
        """One decode step's emitted (request_id, token) pairs."""
        if not pairs:
            return
        toks = [[rid, int(t)] for rid, t in pairs]
        with self._lock:
            for rid, t in toks:
                entry = self._live.get(rid)
                if entry is not None:
                    entry["gen"].append(t)
        self._append({"k": "tok", "toks": toks})

    def record_finish(self, request_id, outcome, tokens=None):
        """The request left the engine. ``tokens`` (full output,
        resume prefix included) rides along for completed requests so
        recovery can return pre-crash results without replaying."""
        rec = {"k": "fin", "id": request_id, "outcome": outcome}
        if tokens is not None:
            rec["toks"] = [int(t) for t in tokens]
        with self._lock:
            self._live.pop(request_id, None)
        self._append(rec)

    # -- the append path ----------------------------------------------------

    def _append(self, rec):
        if self._fh is None:
            return
        try:
            # chaos hook — BEFORE the lock, so an injected delay stalls
            # only this producer, and an injected raise exercises the
            # drop-and-degrade path below
            fault_point("serve.journal_write", record=rec.get("k"))
            line = json.dumps(rec, separators=(",", ":")) + "\n"
            with self._lock:
                self._fh.write(line)  # threadlint: ok[CL003] serialized appends ARE the journal's ordering contract (the _FlightSpill idiom): one buffered line write + flush per record, and record producers are the decode thread + submitters only
                self._fh.flush()  # threadlint: ok[CL003] see above — per-record flush bounds SIGKILL loss to one torn line
                if self.fsync:
                    os.fsync(self._fh.fileno())  # threadlint: ok[CL003] opt-in durability mode; callers choosing fsync chose the stall
                self._bytes += len(line)
                self._records += 1
        except Exception as e:  # noqa: BLE001 — the journal must never
            # kill the serving loop it protects; drop + count + continue
            self._note_error(e)
            return
        if self._bytes > self.max_bytes:
            self._compact()

    def _note_error(self, err):
        self.errors += 1
        record_fault("journal_errors", f"{type(err).__name__}: {err}")

    def _compact(self):
        """Rewrite the journal as one fresh ``sub`` record per live
        request (generated-so-far folded in as ``gen``), via tmp +
        atomic rename. Finished history is dropped — its results were
        delivered when they finished."""
        tmp = f"{self.path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            with self._lock:
                # the whole rewrite runs under the lock: appends racing
                # a half-compacted file would lose records — atomicity
                # here IS the durability contract, stall accepted
                with open(tmp, "w", encoding="utf-8") as fh:  # threadlint: ok[CL003] see above
                    for rid, e in self._live.items():
                        rec = {"k": "sub", "id": rid,
                               "prompt": list(e["prompt"]),
                               "max_new_tokens": e["max_new_tokens"],
                               "eos_id": e["eos_id"],
                               "deadline_s": e["deadline_s"]}
                        if e["gen"]:
                            rec["gen"] = list(e["gen"])
                        fh.write(json.dumps(rec, separators=(",", ":"))  # threadlint: ok[CL003] see above
                                 + "\n")
                    fh.flush()  # threadlint: ok[CL003] see above
                    os.fsync(fh.fileno())  # threadlint: ok[CL003] see above
                self._fh.close()
                os.replace(tmp, self.path)
                self._fh = open(self.path, "a", encoding="utf-8")  # threadlint: ok[CL003] see above
                self._bytes = self._fh.tell()
                self._compactions += 1
        except Exception as e:  # noqa: BLE001 — same contract as appends
            self._note_error(e)
            try:
                os.remove(tmp)
            except OSError:
                pass
            with self._lock:
                if self._fh is None or self._fh.closed:
                    try:
                        self._fh = open(self.path, "a", encoding="utf-8")  # threadlint: ok[CL003] failure-path reopen; one-off by construction
                        self._bytes = self._fh.tell()
                    except OSError:
                        self._fh = None  # journal-less degraded from here

    def stats(self):
        return {"path": self.path, "records": self._records,
                "bytes": self._bytes, "live": len(self._live),
                "compactions": self._compactions, "errors": self.errors,
                "ok": self._fh is not None}

    def close(self):
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()  # threadlint: ok[CL003] shutdown path; no producer left to stall
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def read_journal(path):
    """Fold a journal back into recovery state.

    Returns ``{"unfinished": [spec...], "completed": {id: tokens},
    "outcomes": {id: outcome}}`` where each unfinished spec carries the
    original prompt, stopping params, and ``gen`` (every token emitted
    before the crash, resume prefixes folded in). A torn final line
    (the record a SIGKILL interrupted mid-write) is skipped, as is any
    line that fails to parse — recovery prefers a lost record to a
    wedged restart."""
    entries = {}
    completed = {}
    outcomes = {}
    for rec in iter_jsonl(path):
        k = rec.get("k")
        if k == "sub":
            entries[rec["id"]] = {
                "id": rec["id"], "prompt": list(rec.get("prompt", [])),
                "max_new_tokens": int(rec.get("max_new_tokens", 0)),
                "eos_id": rec.get("eos_id"),
                "deadline_s": rec.get("deadline_s"),
                "gen": [int(t) for t in rec.get("gen", [])]}
        elif k == "tok":
            for rid, t in rec.get("toks", []):
                e = entries.get(rid)
                if e is not None:
                    e["gen"].append(int(t))
        elif k == "fin":
            e = entries.pop(rec.get("id"), None)
            outcomes[rec.get("id")] = rec.get("outcome")
            if rec.get("outcome") == "completed":
                toks = rec.get("toks")
                if toks is None:
                    toks = e["gen"] if e else []
                completed[rec["id"]] = [int(t) for t in toks]
    return {"unfinished": list(entries.values()),
            "completed": completed, "outcomes": outcomes}
