"""Paddle Inference predictor API (Config/Predictor/Tensor handles).

Reference: paddle/fluid/inference + python/paddle/inference/__init__.py —
Config (model paths, memory/threads, optimization switches),
create_predictor, Predictor with named zero-copy input/output handles.

TPU-native: a Predictor wraps a jit.save artifact (StableHLO + params):
the program is AOT-compiled once per input signature (XLA compile cache),
inputs bind as device arrays without host copies ("zero-copy" = the
jax.Array handle IS the binding), outputs stay on device until copy_to_cpu.
Config's GPU/MKLDNN toggles are accepted for parity and ignored.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "PrecisionType", "PlaceType", "get_version"]


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    CPU = 0
    GPU = 1
    TPU = 4
    XPU = 2


class Config:
    """Reference: paddle_infer.Config — model location + engine knobs."""

    def __init__(self, prog_file=None, params_file=None):
        # paddle convention: Config("path/model") or
        # Config("m.pdmodel", "m.pdiparams")
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            self._prefix = prog_file[:-len(".pdmodel")]
        else:
            self._prefix = prog_file
        self._params_file = params_file
        self._precision = PrecisionType.Float32
        self._memory_pool_mb = 0
        self._threads = 1
        self._enable_ir = True
        self._profile = False

    def set_prog_file(self, path):
        self._prefix = path[:-len(".pdmodel")] if path.endswith(".pdmodel") \
            else path

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def set_params_file(self, path):
        self._params_file = path

    def params_file(self):
        return self._params_file or (self._prefix or "") + ".pdiparams"

    # engine knobs (accepted for parity; XLA owns memory/threads on TPU)
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._memory_pool_mb = memory_pool_init_size_mb

    def disable_gpu(self):
        pass

    def use_gpu(self):
        return False

    def enable_memory_optim(self):
        pass

    def set_cpu_math_library_num_threads(self, n):
        self._threads = n

    def switch_ir_optim(self, flag=True):
        self._enable_ir = flag

    def enable_profile(self):
        self._profile = True

    def enable_tensorrt_engine(self, *a, **kw):
        pass  # TensorRT has no TPU meaning; XLA is the optimizing compiler

    def summary(self):
        return (f"Config(prog={self.prog_file()}, "
                f"params={self.params_file()}, threads={self._threads})")


class Tensor:
    """Named zero-copy binding handle (reference: paddle_infer.Tensor)."""

    def __init__(self, predictor, name, is_input):
        self._p = predictor
        self._name = name
        self._is_input = is_input

    def copy_from_cpu(self, arr):
        self._p._inputs[self._name] = jnp.asarray(np.asarray(arr))

    def share_external_data(self, arr):
        # jax.Array binds directly — the handle is the device buffer
        self._p._inputs[self._name] = arr._value if hasattr(arr, "_value") \
            else jnp.asarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._p._outputs[self._name])

    def to_dlpack(self):
        return jax.dlpack.to_dlpack(self._p._outputs[self._name])

    def shape(self):
        src = self._p._inputs if self._is_input else self._p._outputs
        v = src.get(self._name)
        return list(v.shape) if v is not None else None

    def reshape(self, shape):
        pass  # shapes derive from the bound array


class Predictor:
    def __init__(self, config):
        from ..jit import load as jit_load

        self._config = config
        prefix = config._prefix
        if not os.path.exists(prefix + ".pdmodel"):
            raise FileNotFoundError(prefix + ".pdmodel")
        self._layer = jit_load(prefix)
        meta = self._load_meta(prefix)
        n_in = len(meta["in_shapes"]) if meta else 1
        self._in_names = [f"x{i}" for i in range(n_in)]
        self._out_names = []
        self._inputs = {}
        self._outputs = {}

    @staticmethod
    def _load_meta(prefix):
        import pickle

        try:
            with open(prefix + ".pdmodel.meta", "rb") as f:
                return pickle.load(f)
        except OSError:
            return None

    def get_input_names(self):
        return list(self._in_names)

    def get_input_handle(self, name):
        return Tensor(self, name, True)

    def get_output_names(self):
        return list(self._out_names)

    def get_output_handle(self, name):
        return Tensor(self, name, False)

    def run(self, inputs=None):
        """Execute the AOT-compiled program. `inputs` (optional list of
        arrays) is the convenience form; otherwise bound input handles."""
        if inputs is not None:
            args = [jnp.asarray(np.asarray(a)) for a in inputs]
        else:
            args = [self._inputs[n] for n in self._in_names]
        outs = self._layer(*args)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        self._out_names = [f"out{i}" for i in range(len(outs))]
        self._outputs = {n: o._value for n, o in zip(self._out_names, outs)}
        if inputs is not None:
            return [np.asarray(v) for v in self._outputs.values()]
        return True

    def clear_intermediate_tensor(self):
        self._outputs = {}

    def try_shrink_memory(self):
        pass


def create_predictor(config):
    return Predictor(config)


def get_version():
    import paddle_tpu

    return paddle_tpu.__version__


class DataType:
    """Predictor tensor dtypes (reference paddle_infer_declare.h)."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


def get_num_bytes_of_data_type(dtype):
    return {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
            DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
            DataType.BFLOAT16: 2}[dtype]


class PredictorPool:
    """A pool of Predictors sharing one compiled executable (reference
    paddle_inference_api.h PredictorPool). XLA executables are reentrant, so
    the clones share the AOT artifact and differ only in binding state."""

    def __init__(self, config, size=1):
        self._preds = [create_predictor(config) for _ in range(max(1, size))]

    def retrive(self, idx):  # reference spells it 'retrive'
        return self._preds[idx]

    retrieve = retrive


def get_trt_compile_version():
    return (0, 0, 0)  # no TensorRT tier on TPU; XLA AOT serves this role


def get_trt_runtime_version():
    return (0, 0, 0)


__all__ += ["DataType", "PredictorPool", "get_num_bytes_of_data_type",
            "get_trt_compile_version", "get_trt_runtime_version"]
