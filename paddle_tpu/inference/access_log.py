"""Per-request access log for the serving engine (ISSUE 20).

Every request that LEAVES the engine — completed, evicted, cancelled,
or shed by admission control — produces exactly one structured access
record: its phase timeline (queue wait, prefill chunks, preemptions,
first token, per-token decode aggregates), token accounting, and
outcome. Three consumers share the record:

* **durable JSONL file** (when a path is configured): the journal's
  durability contract — one buffered line write + flush per record
  under a private lock, bounded rotation (``access.jsonl`` →
  ``access.jsonl.1`` → ...), a torn final line tolerated on read, and
  a write failure that NEVER raises into the decode loop (dropped +
  ``access_log_errors`` fault + serving continues). The file doubles
  as the replay format for ``tools/loadgen.py --replay``.
* **bounded in-memory ring**: the `/requestz` statusz route's "recent
  requests" table, available with or without a file.
* **process-wide aggregates**: outcome counts and latency/TTFT sums
  built from the SAME measured values the engine feeds into
  ``paddle_tpu_serve_requests_total`` / ``_request_seconds`` /
  ``_ttft_seconds`` — `tracing.reconcile_with_metrics()` checks the
  two surfaces agree EXACTLY (the repo's standing same-measurement
  invariant, extended from spans to access records).

Tail-based trace sampling lives here as one pure, deterministic
predicate: `tail_sampled(outcome, latency_s, slow_s)`. Requests on the
unhappy path (any non-``completed`` outcome) or over the latency
threshold keep full nested ``serve/request/*`` span detail and a
``serve_access`` event in the structured stream; happy-path requests
emit only the summary record, so trace volume stays bounded under
heavy traffic while every slow/shed/evicted request stays explainable.
"""
from __future__ import annotations

import collections
import json
import os
import threading

from ..runtime import telemetry as _telemetry
from ..runtime import tracing as _tracing
from ..runtime.resilience import fault_point, record_fault
from .journal import iter_jsonl

__all__ = ["AccessLog", "read_access_log", "tail_sampled",
           "aggregates", "reset_aggregates"]


def tail_sampled(outcome, latency_s, slow_s):
    """The tail-sampling decision — pure and deterministic: the same
    (outcome, latency, threshold) always samples the same way, so a
    record's ``sampled`` flag fully explains why its trace detail
    exists (or doesn't). Unhappy-path outcomes always sample; completed
    requests sample only past the slow threshold (None disables the
    slow path, sampling errors/sheds only)."""
    if outcome != "completed":
        return True
    if slow_s is None or latency_s is None:
        return False
    return float(latency_s) >= float(slow_s)


class _Aggregates:
    """Process-wide access-record aggregates, mirrored 1:1 against the
    outcome counter and latency/TTFT histograms for exact
    reconciliation. `latency_s`/`ttft_s` must be the value the caller
    fed the matching histogram, or None when that exit path does not
    observe the histogram (a submit-time shed increments the outcome
    counter but never entered `paddle_tpu_serve_request_seconds`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.outcomes = {}
        self.latency_sum = 0.0
        self.latency_count = 0
        self.ttft_sum = 0.0
        self.ttft_count = 0

    def add(self, outcome, latency_s=None, ttft_s=None):
        with self._lock:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
            if latency_s is not None:
                self.latency_sum += float(latency_s)
                self.latency_count += 1
            if ttft_s is not None:
                self.ttft_sum += float(ttft_s)
                self.ttft_count += 1

    def snapshot(self):
        with self._lock:
            return {"outcomes": dict(self.outcomes),
                    "latency_sum": self.latency_sum,
                    "latency_count": self.latency_count,
                    "ttft_sum": self.ttft_sum,
                    "ttft_count": self.ttft_count}

    def reset(self):
        with self._lock:
            self.outcomes = {}
            self.latency_sum = 0.0
            self.latency_count = 0
            self.ttft_sum = 0.0
            self.ttft_count = 0


_AGG = _Aggregates()


def aggregates():
    """The process-wide access aggregates (reconciliation probe)."""
    return _AGG.snapshot()


def reset_aggregates():
    _AGG.reset()


# reconciliation wiring: tracing compares these aggregates against the
# registry counters without importing the inference package (layering:
# inference -> runtime only). reset_metrics() clears both sides, so the
# exactness invariant survives test isolation.
_tracing.set_serve_access_probe(aggregates)
_telemetry.on_reset(reset_aggregates)


class AccessLog:
    """One engine's access-record sink: aggregates + ring always; a
    durable JSONL file when `path` is configured."""

    def __init__(self, path=None, max_bytes=4 << 20, max_files=3,
                 ring=256):
        self.path = os.path.abspath(str(path)) if path else None
        self.max_bytes = int(max_bytes)
        self.max_files = max(1, int(max_files))
        self.ring = collections.deque(maxlen=int(ring))
        self._lock = threading.Lock()
        self._fh = None
        self.records = 0
        self.rotations = 0
        self.errors = 0
        if self.path:
            parent = os.path.dirname(self.path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            try:
                self._fh = open(self.path, "a", encoding="utf-8")
            except OSError as e:
                self._note_error(e)

    def record(self, rec, latency_s=None, ttft_s=None):
        """Ingest one exit record. `latency_s`/`ttft_s` are the exact
        values the engine fed the matching histograms (None = that
        histogram was not observed on this exit path); the record dict
        itself is what lands in the ring and the file."""
        _AGG.add(rec.get("outcome", "unknown"),
                 latency_s=latency_s, ttft_s=ttft_s)
        self.ring.append(rec)  # deque append: GIL-atomic, bounded
        self._append(rec)

    def _append(self, rec):
        if self._fh is None:
            return
        try:
            # chaos hook — BEFORE the lock (the journal idiom): an
            # injected delay stalls only this producer, an injected
            # raise exercises drop-and-degrade
            fault_point("serve.access_write",
                        outcome=rec.get("outcome"))
            line = json.dumps(rec, separators=(",", ":"),
                              default=str) + "\n"
            with self._lock:
                self._fh.write(line)  # threadlint: ok[CL003] the journal's durability idiom: one buffered line write + flush per record under the private lock; producers are the decode thread + submitters only
                self._fh.flush()  # threadlint: ok[CL003] see above — per-record flush bounds SIGKILL loss to one torn line
                self.records += 1
                if self.max_bytes and self._fh.tell() >= self.max_bytes:
                    self._rotate()
        except Exception as e:  # noqa: BLE001 — observability must
            # never kill the serving loop it observes
            self._note_error(e)

    def _rotate(self):
        """EventStream-style generation shift (caller holds the lock):
        readers see whole generations or nothing, never half a file."""
        self._fh.close()
        if self.max_files == 1:
            self._fh = open(self.path, "w", encoding="utf-8")  # threadlint: ok[CL003] single-file bound: truncation under the writer lock IS the rotation contract; read_access_log tolerates it
            self.rotations += 1
            return
        for i in range(self.max_files - 1, 0, -1):
            src = self.path if i == 1 else f"{self.path}.{i - 1}"
            try:
                os.replace(src, f"{self.path}.{i}")
            except OSError:
                pass
        self._fh = open(self.path, "a", encoding="utf-8")  # threadlint: ok[CL003] rotation must swap the file atomically w.r.t. writers — the append caller holds the lock by design
        self.rotations += 1

    def _note_error(self, err):
        self.errors += 1
        record_fault("access_log_errors", f"{type(err).__name__}: {err}")

    def recent(self, n=50):
        """Newest-last slice of the in-memory ring."""
        return list(self.ring)[-int(n):]

    def stats(self):
        return {"path": self.path, "records": self.records,
                "ring": len(self.ring), "rotations": self.rotations,
                "errors": self.errors,
                "ok": self._fh is not None or self.path is None}

    def close(self):
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()  # threadlint: ok[CL003] shutdown path; no producer left to stall
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def read_access_log(path, include_rotated=True):
    """Parse access records back, oldest first, rotated generations
    included. Tolerates a torn final line (the SIGKILL contract) and
    skips any line that fails to parse."""
    paths = []
    if include_rotated:
        i = 1
        while os.path.exists(f"{path}.{i}"):
            paths.append(f"{path}.{i}")
            i += 1
        paths.reverse()
    paths.append(path)
    out = []
    for p in paths:
        out.extend(iter_jsonl(p))
    return out
