"""Decoder model for the serving engine.

`TinyServeModel` is a small pre-LN causal transformer LM whose
attention reads/writes the paged KV cache through the ragged op
(nn/functional/attention.py `ragged_paged_attention`). Every tensor op
goes through `core.autograd.apply`, so the decode step rides the whole
runtime spine for free: jit-cached per-op dispatch, trace-fusion
(`PADDLE_TPU_EAGER_FUSION=1` records the many tiny decode ops and
flushes ONE fused XLA program per step), warm-start manifest entries at
every fresh build (the op callables are module-level, so entries replay
in a fresh process), and sampled per-op runtime attribution.

The forward is padding-free: it consumes the scheduler's ragged rows
(`[T]` tokens with per-row request slot + position) directly, so a step
mixing a 7-token prefill chunk with three decode tokens costs T=10 rows
plus the fixed token-budget tail — never a [batch, max_seq] rectangle.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from .kv_cache import KVCacheConfig

__all__ = ["TinyServeModel"]


def _t(arr):
    import jax.numpy as jnp

    return Tensor(jnp.asarray(arr))


def _embed(tok, pos, ew, pw):
    import jax.numpy as jnp

    safe = jnp.clip(pos, 0, pw.shape[0] - 1)
    return jnp.take(ew, tok, axis=0) + jnp.take(pw, safe, axis=0)


_embed.__name__ = "serve_embed"


def _ln(v, w, b):
    import jax.numpy as jnp

    mu = v.mean(-1, keepdims=True)
    var = ((v - mu) ** 2).mean(-1, keepdims=True)
    return (v - mu) / jnp.sqrt(var + 1e-5) * w + b


_ln.__name__ = "serve_layer_norm"


def _qkv_proj(v, w):
    import jax.numpy as jnp

    return jnp.split(v @ w, 3, axis=-1)


_qkv_proj.__name__ = "serve_qkv_proj"


def _proj(v, w):
    return v @ w


_proj.__name__ = "serve_proj"


def _mlp(v, w1, b1, w2, b2):
    import jax.numpy as jnp

    return jnp.tanh(v @ w1 + b1) @ w2 + b2


_mlp.__name__ = "serve_mlp"


def _add(a, b):
    return a + b


_add.__name__ = "serve_residual"


class TinyServeModel:
    """Deterministically initialized causal LM for serving tests,
    smokes, and benches (the engine itself is model-agnostic: anything
    exposing `kv_config()` + `forward(...)` with this contract serves).

    Geometry: `dim` must divide by `heads`; KV heads == query heads
    (MQA/GQA is out of scope for the CPU-correctness tier)."""

    def __init__(self, vocab=64, dim=16, layers=2, heads=2, ffn=32,
                 max_pos=256, seed=0):
        if dim % heads:
            raise ValueError("dim must be divisible by heads")
        self.vocab, self.dim, self.layers = int(vocab), int(dim), int(layers)
        self.heads, self.ffn, self.max_pos = int(heads), int(ffn), int(max_pos)
        self.head_dim = self.dim // self.heads
        rng = np.random.RandomState(seed)

        def w(*shape, scale=0.05):
            return _t((rng.randn(*shape) * scale).astype(np.float32))

        self.params = {"embed": w(vocab, dim, scale=0.1),
                       "pos": w(max_pos, dim, scale=0.02),
                       "lnf_w": _t(np.ones(dim, np.float32)),
                       "lnf_b": _t(np.zeros(dim, np.float32)),
                       "head": w(dim, vocab, scale=0.1)}
        for i in range(self.layers):
            self.params.update({
                f"l{i}_ln1_w": _t(np.ones(dim, np.float32)),
                f"l{i}_ln1_b": _t(np.zeros(dim, np.float32)),
                f"l{i}_wqkv": w(dim, 3 * dim),
                f"l{i}_wo": w(dim, dim),
                f"l{i}_ln2_w": _t(np.ones(dim, np.float32)),
                f"l{i}_ln2_b": _t(np.zeros(dim, np.float32)),
                f"l{i}_w1": w(dim, ffn),
                f"l{i}_b1": _t(np.zeros(ffn, np.float32)),
                f"l{i}_w2": w(ffn, dim),
                f"l{i}_b2": _t(np.zeros(dim, np.float32))})

    def kv_config(self, block_size=16, num_blocks=64,
                  max_blocks_per_seq=None):
        return KVCacheConfig(num_layers=self.layers, num_heads=self.heads,
                             head_dim=self.head_dim, block_size=block_size,
                             num_blocks=num_blocks,
                             max_blocks_per_seq=max_blocks_per_seq)

    def forward(self, token_ids, row_req, row_pos, cache, tables,
                decode_only=False):
        """One ragged step. `token_ids`/`row_req`/`row_pos`: i32 Tensors
        `[T]` (padding rows: token 0, pos -1); `cache`: PagedKVCache
        (pools are read AND rebound — the KV write is part of the op);
        `tables`: i32 Tensor `[R, max_blocks_per_seq]`. Returns logits
        Tensor `[T, vocab]`."""
        from ..core.autograd import apply
        from ..nn.functional.attention import ragged_paged_attention

        p = self.params
        x = apply(_embed, token_ids, row_pos, p["embed"], p["pos"])
        for i in range(self.layers):
            h = apply(_ln, x, p[f"l{i}_ln1_w"], p[f"l{i}_ln1_b"])
            q, k, v = apply(_qkv_proj, h, p[f"l{i}_wqkv"])
            kp, vp = cache.layer(i)
            attn, kp2, vp2 = ragged_paged_attention(
                q, k, v, kp, vp, tables, row_req, row_pos,
                num_heads=self.heads, decode_only=decode_only)
            cache.set_layer(i, kp2, vp2)
            x = apply(_add, x, apply(_proj, attn, p[f"l{i}_wo"]))
            h2 = apply(_ln, x, p[f"l{i}_ln2_w"], p[f"l{i}_ln2_b"])
            x = apply(_add, x, apply(_mlp, h2, p[f"l{i}_w1"], p[f"l{i}_b1"],
                                     p[f"l{i}_w2"], p[f"l{i}_b2"]))
        x = apply(_ln, x, p["lnf_w"], p["lnf_b"])
        return apply(_proj, x, p["head"])
