"""Block-allocated paged KV cache (ROADMAP item 1; PAPERS.md "Ragged
Paged Attention").

The cache owns two device pools per transformer layer, each shaped
``[num_blocks, block_size, num_heads, head_dim]``. A request's context
lives in a *block table* — an ordered list of block ids — so logically
contiguous token positions map to physically scattered fixed-size
blocks; admitting a request allocates blocks lazily as its context
grows, evicting frees them all at once. No slab is ever resized or
copied: continuous batching admits/evicts per decode iteration and the
only allocator work is list ops on integer block ids.

Pools are functional jax state: the ragged attention op returns updated
pools and the engine rebinds them via ``set_layer`` — so the cache
composes with jit-cached dispatch and trace-fusion like every other
tensor in the runtime (no in-place device mutation to invalidate a
trace).

Block ids are allocated lowest-id-first, which makes allocation
deterministic: a batched run and a sequential replay of the same
admission order produce identical block tables. Nothing downstream
depends on that (attention gathers through the table), but determinism
keeps the token-exactness acceptance test honest about what it proves.
"""
from __future__ import annotations

import heapq
import threading

import numpy as np

from ..core.tensor import Tensor
from ..runtime import telemetry as _telemetry
from ..runtime.resilience import fault_point

__all__ = ["KVCacheConfig", "PagedKVCache"]


class KVCacheConfig:
    """Static geometry of the paged cache.

    ``max_blocks_per_seq`` bounds one request's context at
    ``max_blocks_per_seq * block_size`` tokens and fixes the block-table
    width (ragged tables pad to it so every step keeps one stable shape
    for the jit cache)."""

    def __init__(self, num_layers, num_heads, head_dim, block_size=16,
                 num_blocks=64, max_blocks_per_seq=None, dtype="float32"):
        if block_size <= 0 or num_blocks <= 0:
            raise ValueError("block_size and num_blocks must be positive")
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_blocks_per_seq = int(max_blocks_per_seq or num_blocks)
        self.dtype = dtype

    @property
    def max_context(self):
        return self.max_blocks_per_seq * self.block_size

    def __repr__(self):
        return (f"KVCacheConfig(layers={self.num_layers}, "
                f"heads={self.num_heads}, head_dim={self.head_dim}, "
                f"block={self.block_size}x{self.num_blocks})")


class PagedKVCache:
    """Fixed-size-block KV store + per-request block tables.

    Allocator state is host-side (plain ints under a lock — the
    scheduler calls from the step loop only, but gauges are read from
    exporter threads); tensor pools are device-side and purely
    functional."""

    def __init__(self, config: KVCacheConfig):
        import jax.numpy as jnp

        from ..core import dtype as dtypes

        self.config = config
        jdt = dtypes.to_jax_dtype(config.dtype)
        shape = (config.num_blocks, config.block_size,
                 config.num_heads, config.head_dim)
        zeros = jnp.zeros(shape, jdt)
        self._k = [Tensor(zeros) for _ in range(config.num_layers)]
        self._v = [Tensor(zeros) for _ in range(config.num_layers)]
        self._lock = threading.Lock()
        self._free = list(range(config.num_blocks))  # kept a heap
        heapq.heapify(self._free)
        self._tables = {}        # request id -> [block ids]
        self._highwater = 0
        self._alloc_total = 0
        self._free_total = 0
        # bumped on every table mutation (alloc/release): the engine
        # keys its device-resident padded-tables cache on this, so
        # steady-state decode steps skip the redundant H2D transfer
        self._alloc_version = 0
        self._gauge = _telemetry.gauge(
            "paddle_tpu_serve_kv_blocks", "paged KV cache blocks",
            ("state",))
        self._publish()

    # -- allocator ----------------------------------------------------------

    def _publish(self):
        used = self.config.num_blocks - len(self._free)
        self._gauge.labels(state="in_use").set(used)
        self._gauge.labels(state="free").set(len(self._free))
        self._gauge.labels(state="highwater").set(self._highwater)

    def blocks_free(self):
        with self._lock:
            return len(self._free)

    def blocks_in_use(self):
        with self._lock:
            return self.config.num_blocks - len(self._free)

    def utilization(self):
        with self._lock:
            used = self.config.num_blocks - len(self._free)
        return used / float(self.config.num_blocks)

    def blocks_for(self, num_tokens):
        """Blocks needed to hold `num_tokens` context positions."""
        bs = self.config.block_size
        return (int(num_tokens) + bs - 1) // bs

    def ensure_capacity(self, request_id, num_tokens):
        """Grow `request_id`'s block table to cover `num_tokens` context
        positions. Returns True on success; False (allocating nothing)
        when the pool cannot supply the missing blocks or the request
        would exceed ``max_blocks_per_seq`` — the scheduler's cue to
        defer or preempt."""
        # chaos hook (BEFORE the lock — an injected delay must not
        # serialize readers): an injected raise here looks to the
        # scheduler exactly like pool exhaustion
        fault_point("serve.kv_alloc", request=str(request_id),
                    tokens=int(num_tokens))
        need = self.blocks_for(num_tokens)
        if need > self.config.max_blocks_per_seq:
            return False
        with self._lock:
            table = self._tables.setdefault(request_id, [])
            missing = need - len(table)
            if missing <= 0:
                return True
            if missing > len(self._free):
                return False
            for _ in range(missing):
                table.append(heapq.heappop(self._free))
            self._alloc_total += missing
            self._alloc_version += 1
            used = self.config.num_blocks - len(self._free)
            self._highwater = max(self._highwater, used)
            self._publish()
        return True

    def release(self, request_id):
        """Free every block the request holds (evict/finish). Unknown
        ids are a no-op so double-release cannot corrupt the free list.
        Returns the number of blocks freed."""
        with self._lock:
            table = self._tables.pop(request_id, None)
            if not table:
                return 0
            for b in table:
                heapq.heappush(self._free, b)
            self._free_total += len(table)
            self._alloc_version += 1
            self._publish()
            return len(table)

    def alloc_version(self):
        """Monotonic table-mutation counter (see __init__ note)."""
        with self._lock:
            return self._alloc_version

    def block_table(self, request_id):
        with self._lock:
            return list(self._tables.get(request_id, ()))

    def num_requests(self):
        with self._lock:
            return len(self._tables)

    def padded_tables(self, request_ids):
        """i32 ``[len(request_ids), max_blocks_per_seq]`` block-table
        matrix, one row per running slot, unused entries 0 (never read:
        the attention op masks context positions past each row's token
        position, which the allocator guarantees are covered by real
        table entries)."""
        out = np.zeros((len(request_ids), self.config.max_blocks_per_seq),
                       np.int32)
        with self._lock:
            for i, rid in enumerate(request_ids):
                table = self._tables.get(rid, ())
                out[i, :len(table)] = table
        return out

    def stats(self):
        with self._lock:
            used = self.config.num_blocks - len(self._free)
            return {"num_blocks": self.config.num_blocks,
                    "block_size": self.config.block_size,
                    "blocks_in_use": used,
                    "blocks_free": len(self._free),
                    "utilization": used / float(self.config.num_blocks),
                    "highwater": self._highwater,
                    "requests": len(self._tables),
                    "allocs_total": self._alloc_total,
                    "frees_total": self._free_total}

    # -- device pools -------------------------------------------------------

    def layer(self, i):
        """(k_pool, v_pool) Tensors for layer `i`."""
        return self._k[i], self._v[i]

    def set_layer(self, i, k_pool, v_pool):
        """Rebind layer `i`'s pools to the op-returned updated tensors."""
        self._k[i] = k_pool
        self._v[i] = v_pool

    def reset_pools(self):
        """Zero the device pools (tests); allocator state is untouched."""
        import jax.numpy as jnp

        for i in range(self.config.num_layers):
            z = jnp.zeros_like(self._k[i]._value)
            self._k[i] = Tensor(z)
            self._v[i] = Tensor(z)
