"""paddle.sparse.layer.

Reference: python/paddle/sparse/layer/activation.py (ReLU).
"""
from __future__ import annotations

from ...nn.layer.layers import Layer
from .. import functional as F

__all__ = ["ReLU"]


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)
