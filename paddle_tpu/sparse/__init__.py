"""paddle.sparse — BCOO/BCSR-backed sparse tensors.

Reference: python/paddle/sparse/__init__.py (sparse_coo_tensor,
sparse_csr_tensor, ReLU).
"""
from . import functional  # noqa: F401
from .creation import (  # noqa: F401
    SparseCooTensor, SparseCsrTensor, sparse_coo_tensor, sparse_csr_tensor,
    to_sparse_coo,
)
from .functional import masked_matmul, matmul, relu  # noqa: F401
from .layer import ReLU  # noqa: F401

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "ReLU",
           "SparseCooTensor", "SparseCsrTensor",
           "relu", "matmul", "masked_matmul"]
