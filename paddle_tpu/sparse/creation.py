"""Sparse tensor creation.

Reference: python/paddle/sparse/creation.py:42 (sparse_coo_tensor) and :115
(sparse_csr_tensor). TPU-native design: payloads are
jax.experimental.sparse BCOO/BCSR arrays — XLA-compilable sparse formats
whose matmuls lower to gather + MXU dot_general, so sparse compute stays on
device instead of a host scatter loop.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core import dtype as dtypes
from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor"]


def _as_jnp(x, dtype=None):
    if isinstance(x, Tensor):
        x = x._value
    v = jnp.asarray(x)
    if dtype is not None:
        v = v.astype(dtypes.to_jax_dtype(dtype))
    return v


def _infer_dense_shape(indices, values):
    lo = tuple(int(d) + 1 for d in np.asarray(indices.max(axis=1)))
    return lo + tuple(values.shape[1:])


class SparseCooTensor:
    """COO sparse tensor: [sparse_dim, nnz] indices + [nnz, ...] values."""

    def __init__(self, bcoo):
        self._bcoo = bcoo

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return dtypes.to_paddle_dtype(self._bcoo.dtype)

    def indices(self):
        return Tensor(self._bcoo.indices.T)  # paddle layout [ndim, nnz]

    def values(self):
        return Tensor(self._bcoo.data)

    def nnz(self):
        return int(self._bcoo.nse)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def to_sparse_csr(self):
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(self._bcoo))

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse tensor: crows/cols/values (2D, or batched 3D)."""

    def __init__(self, bcsr):
        self._bcsr = bcsr

    @property
    def shape(self):
        return list(self._bcsr.shape)

    @property
    def dtype(self):
        return dtypes.to_paddle_dtype(self._bcsr.dtype)

    def crows(self):
        return Tensor(self._bcsr.indptr)

    def cols(self):
        return Tensor(self._bcsr.indices)

    def values(self):
        return Tensor(self._bcsr.data)

    def nnz(self):
        return int(self._bcsr.nse)

    def to_dense(self):
        return Tensor(self._bcsr.todense())

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def to_sparse_coo(self, sparse_dim=None):
        return SparseCooTensor(self._bcsr.to_bcoo())

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    idx = _as_jnp(indices)
    if idx.dtype not in (jnp.int32, jnp.int64):
        idx = idx.astype(jnp.int32)
    vals = _as_jnp(values, dtype)
    if idx.ndim != 2:
        raise ValueError("indices must be [sparse_dim, nnz]")
    if shape is None:
        shape = _infer_dense_shape(idx, vals)
    bcoo = jsparse.BCOO((vals, idx.T), shape=tuple(int(s) for s in shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    crows = _as_jnp(crows)
    cols = _as_jnp(cols)
    vals = _as_jnp(values, dtype)
    shape = tuple(int(s) for s in shape)
    if crows.dtype not in (jnp.int32, jnp.int64):
        crows = crows.astype(jnp.int32)
    if cols.dtype not in (jnp.int32, jnp.int64):
        cols = cols.astype(jnp.int32)
    bcsr = jsparse.BCSR((vals, cols, crows), shape=shape)
    return SparseCsrTensor(bcsr)


def to_sparse_coo(dense, sparse_dim):
    """Dense Tensor -> SparseCooTensor with `sparse_dim` leading sparse axes."""
    v = dense._value if isinstance(dense, Tensor) else jnp.asarray(dense)
    n_dense = v.ndim - int(sparse_dim)
    bcoo = jsparse.BCOO.fromdense(v, n_dense=n_dense)
    return SparseCooTensor(bcoo)
