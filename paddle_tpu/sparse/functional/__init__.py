"""paddle.sparse.functional.

Reference: python/paddle/sparse/functional/activation.py:20 (relu). Extended
with matmul/masked_matmul mirroring the phi sparse kernel capability
(paddle/phi/kernels/sparse/) — on TPU these lower through BCOO dot_general
so the dense side rides the MXU.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ...core.tensor import Tensor
from ..creation import SparseCooTensor, SparseCsrTensor

__all__ = ["relu", "matmul", "masked_matmul"]


def _map_values(x, fn):
    if isinstance(x, SparseCooTensor):
        b = x._bcoo
        return SparseCooTensor(jsparse.BCOO((fn(b.data), b.indices),
                                            shape=b.shape))
    if isinstance(x, SparseCsrTensor):
        b = x._bcsr
        return SparseCsrTensor(jsparse.BCSR((fn(b.data), b.indices, b.indptr),
                                            shape=b.shape))
    raise TypeError(f"expected sparse tensor, got {type(x)}")


def relu(x, name=None):
    """Elementwise relu on the stored values (zeros stay zero)."""
    return _map_values(x, lambda v: jnp.maximum(v, 0))


def matmul(x, y, name=None):
    """Sparse @ dense -> dense. x: SparseCoo/CsrTensor, y: dense Tensor."""
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    if not isinstance(x, SparseCooTensor):
        raise TypeError(f"expected sparse lhs, got {type(x)}")
    return Tensor(x._bcoo @ yv)


def masked_matmul(x, y, mask, name=None):
    """(dense @ dense) sampled at mask's sparsity pattern (SDDMM)."""
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    as_csr = isinstance(mask, SparseCsrTensor)
    if as_csr:
        mask = mask.to_sparse_coo()
    if not isinstance(mask, SparseCooTensor):
        raise TypeError(f"expected sparse mask, got {type(mask)}")
    bcoo = mask._bcoo
    data = jsparse.bcoo_dot_general_sampled(
        xv, yv, bcoo.indices,
        dimension_numbers=(((xv.ndim - 1,), (yv.ndim - 2,)), ((), ())))
    out = SparseCooTensor(jsparse.BCOO((data, bcoo.indices),
                                       shape=bcoo.shape))
    return out.to_sparse_csr() if as_csr else out
