"""paddle.profiler.

Reference: python/paddle/profiler/profiler.py:33 — Profiler with
ProfilerTarget/ProfilerState, make_scheduler, RecordEvent annotations,
chrome-trace export.

TPU-native: wraps jax.profiler — traces carry XLA device timelines
(per-op HBM/MXU activity) viewable in TensorBoard/Perfetto, strictly more
detail than the reference's chrome trace. RecordEvent lowers to
jax.profiler.TraceAnnotation so user spans land on the same timeline.
"""
from __future__ import annotations

import enum
import os
import time
import warnings

import jax

from ..core.dispatch import dispatch_stats, reset_dispatch_stats
from ..runtime.resilience import fault_events, fault_log, reset_fault_events

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "SummaryView", "summary_dict",
           "dispatch_stats", "reset_dispatch_stats",
           "fault_events", "fault_log", "reset_fault_events"]


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    TPU = 2


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SummaryView(enum.Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """Step-state scheduler (reference make_scheduler signature)."""
    cycle = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle if cycle else 0
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


class RecordEvent:
    """User span on the profiler timeline (reference RecordEvent)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ann = None
        self.begin_time = None
        self.end_time = None

    def begin(self):
        self.begin_time = time.time()
        try:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:  # profiling unavailable on this backend
            self._ann = None

    def end(self):
        self.end_time = time.time()
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def _input_pipeline_stats():
    """(prefetch_stats-or-None, h2d-hist-summary-or-None) for the
    summary surfaces; both None when the pipeline never ran."""
    from ..io import prefetch as _pf
    from ..runtime import telemetry as _t

    pf = _pf.prefetch_stats()
    if not pf["prefetchers"]:
        pf = None
    h2d = None
    fam = _t.snapshot().get("paddle_tpu_h2d_seconds")
    if fam and fam.get("series") and fam["series"][0].get("count"):
        s = fam["series"][0]
        h2d = {"sum_s": float(s["sum"]), "count": int(s["count"])}
    return pf, h2d


def summary_dict(op_detail=True, top=5):
    """Machine-readable twin of `Profiler.summary()`: the same runtime
    sections (dispatch cache, trace fusion incl. flush reasons+sites,
    warm-start compile, unjittable ops, fault events, telemetry,
    span timeline) as ONE json-serializable dict. This is what the
    diagnostics `/statusz` route serves and what
    ``python -m paddle_tpu.profiler --json`` prints — external tooling
    reads this instead of scraping the printed text."""
    from ..runtime import telemetry as _t
    from ..runtime import tracing as _tr

    ds = dispatch_stats()
    fwd, bwd = ds["forward"], ds["backward"]
    out = {
        "summary_version": 1,
        "dispatch": {
            "forward": dict(fwd),
            "backward": dict(bwd),
        },
        "fusion": None,
        "compile": None,
        "unjittable": ds.get("unjittable"),
        "fault_events": {k: v for k, v in
                         ds.get("fault_events", {}).items() if v},
        "telemetry": None,
        "input_pipeline": None,
        "spans": None,
    }
    pf, h2d = _input_pipeline_stats()
    if pf is not None or h2d is not None:
        out["input_pipeline"] = {"prefetch": pf, "h2d": h2d}
    per_op = ds.get("per_op") or {}
    if op_detail and per_op:
        out["dispatch"]["retrace_heavy_ops"] = {
            k: v["retraces"] for k, v in per_op.items()
            if v["retraces"] > 2}
        occ = sorted(per_op.items(),
                     key=lambda kv: -(kv[1]["cache_entries"]
                                      + kv[1]["bwd_cache_entries"]))[:top]
        out["dispatch"]["cache_occupancy"] = [
            {"op": k, "fwd_programs": v["cache_entries"],
             "bwd_programs": v["bwd_cache_entries"]}
            for k, v in occ
            if v["cache_entries"] + v["bwd_cache_entries"]]
        run = sorted(
            ((k, v) for k, v in per_op.items() if v.get("run_samples")),
            key=lambda kv: -(kv[1]["run_s"] / kv[1]["run_samples"]))[:top]
        out["dispatch"]["run_time_heavy_ops"] = [
            {"op": k, "avg_run_ms": v["run_s"] / v["run_samples"] * 1e3,
             "samples": v["run_samples"]} for k, v in run]
    fus = ds.get("fusion") or {}
    if fus and (fus.get("recorded_ops") or fus.get("enabled")):
        out["fusion"] = dict(fus)
    comp = ds.get("compile") or {}
    if comp:
        comp = dict(comp)
        if op_detail and comp.get("per_op_compile_s"):
            comp["per_op_compile_s"] = dict(sorted(
                comp["per_op_compile_s"].items(),
                key=lambda kv: -kv[1])[:max(top, 10)])
        out["compile"] = comp
    if _t.enabled():
        snap = _t.snapshot()
        stream = _t.event_stream()
        tel = {}
        steps = snap.get("paddle_tpu_train_steps_total")
        if steps and steps["series"]:
            tel["train_steps"] = int(steps["series"][0]["value"])
        hist = snap.get("paddle_tpu_step_seconds")
        if hist and hist["series"] and hist["series"][0]["count"]:
            s = hist["series"][0]
            tel["step_avg_ms"] = s["sum"] / s["count"] * 1e3
            tel["step_count"] = int(s["count"])
        dw = snap.get("paddle_tpu_data_wait_seconds")
        if dw and dw["series"] and dw["series"][0]["count"]:
            s = dw["series"][0]
            tel["data_wait_s"] = s["sum"]
            tel["data_wait_batches"] = int(s["count"])
        if stream is not None:
            tel["events_emitted"] = stream.emitted
            tel["events_path"] = stream.path
        tel["metric_families"] = len(snap)
        out["telemetry"] = tel
    else:
        out["telemetry"] = {"enabled": False}
    st = _tr.span_stats()
    if st:
        rows = sorted(st.items(), key=lambda kv: -kv[1]["self_s"])[:top]
        out["spans"] = {
            "phase_totals_s": _tr.phase_totals(),
            "top_self": [{"span": f"{cat}/{name}",
                          "self_s": v["self_s"], "count": v["count"]}
                         for (cat, name), v in rows],
            "trace_path": _tr.trace_path(),
        }
    return out


class Profiler:
    """paddle.profiler.Profiler over jax.profiler traces.

    on_trace_ready receives the profiler after each RECORD_AND_RETURN step;
    the trace directory holds the TensorBoard/Perfetto artifacts.
    """

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        if callable(scheduler):
            self._scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            lo, hi = scheduler
            self._scheduler = make_scheduler(closed=lo, ready=0,
                                             record=hi - lo, repeat=1)
        else:
            self._scheduler = None  # record from start() to stop()
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._dir = None
        self._recording = False
        self._step = 0
        self._step_times = []
        self._last_step_t = None
        self.current_state = ProfilerState.CLOSED

    # -- trace control -----------------------------------------------------
    def _trace_dir(self):
        if self._dir is None:
            self._dir = os.path.join(
                os.environ.get("PADDLE_PROFILER_DIR", "profiler_log"),
                time.strftime("%Y%m%d_%H%M%S"))
            os.makedirs(self._dir, exist_ok=True)
        return self._dir

    def _start_trace(self):
        if self._recording or self._timer_only:
            return
        try:
            jax.profiler.start_trace(self._trace_dir())
            self._recording = True
        except Exception as e:  # noqa: BLE001 — backend without profiling
            warnings.warn(f"jax.profiler trace unavailable: {e}")

    def _stop_trace(self):
        if not self._recording:
            return
        try:
            jax.profiler.stop_trace()
        finally:
            self._recording = False

    def start(self):
        self.current_state = ProfilerState.RECORD
        self._last_step_t = time.time()
        if self._scheduler is None:
            self._start_trace()

    def stop(self):
        self._stop_trace()
        self.current_state = ProfilerState.CLOSED
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.time()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self._step += 1
        if self._scheduler is None:
            return
        state = self._scheduler(self._step)
        prev = self.current_state
        self.current_state = state
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) \
                and prev in (ProfilerState.CLOSED, ProfilerState.READY):
            self._start_trace()
        if state == ProfilerState.RECORD_AND_RETURN or (
                state == ProfilerState.CLOSED and self._recording):
            self._stop_trace()
            if self._on_trace_ready is not None:
                self._on_trace_ready(self)

    def step_info(self, unit=None):
        if not self._step_times:
            return "step: n/a"
        avg = sum(self._step_times) / len(self._step_times)
        return (f"step {self._step}: avg {avg * 1e3:.2f} ms "
                f"({1.0 / avg:.2f} steps/s)")

    def summary_dict(self, op_detail=True, top=5):
        """The module-level `summary_dict()` plus this profiler's own
        step timing — the machine-readable twin of `summary()`."""
        out = summary_dict(op_detail=op_detail, top=top)
        step = {"steps": self._step}
        if self._step_times:
            avg = sum(self._step_times) / len(self._step_times)
            step.update(avg_ms=avg * 1e3, steps_per_sec=1.0 / avg)
        out["step"] = step
        if self._dir:
            out["trace_artifacts"] = self._dir
        return out

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None):
        print(self.step_info())
        ds = dispatch_stats()
        fwd, bwd = ds["forward"], ds["backward"]
        hr = fwd["hit_rate"]
        print(f"eager dispatch cache: fwd {fwd['hits']} hits / "
              f"{fwd['misses']} misses"
              + (f" ({hr:.1%} hit rate)" if hr is not None else "")
              + f", bwd {bwd['hits']}/{bwd['misses']}, "
              f"{fwd['size']}+{bwd['size']} cached programs")
        if op_detail and ds["per_op"]:
            churn = {k: v for k, v in ds["per_op"].items()
                     if v["retraces"] > 2}
            if churn:
                print(f"  retrace-heavy ops (dynamic shapes?): {churn}")
            # per-op cache occupancy: which ops own the compiled-program
            # budget (a top entry with many programs = shape churn)
            fat = sorted(ds["per_op"].items(),
                         key=lambda kv: -(kv[1]["cache_entries"]
                                          + kv[1]["bwd_cache_entries"]))[:5]
            fat = [(k, v["cache_entries"], v["bwd_cache_entries"])
                   for k, v in fat
                   if v["cache_entries"] + v["bwd_cache_entries"]]
            if fat:
                print("  cache occupancy (op: fwd+bwd programs): "
                      + ", ".join(f"{k}: {f}+{b}" for k, f, b in fat))
        fus = ds.get("fusion") or {}
        if fus and (fus.get("recorded_ops") or fus.get("enabled")):
            # trace-fusion health: how many eager ops were deferred,
            # how often (and why) traces flushed, and whether steady
            # state replays cached fused programs
            n_flush = sum((fus.get("flushes") or {}).values())
            line = (f"trace fusion: {fus.get('recorded_ops', 0)} ops "
                    f"recorded, {n_flush} flushes")
            if fus.get("avg_trace_len"):
                line += f" (avg {fus['avg_trace_len']:.1f} ops/trace)"
            fc = fus.get("fused") or {}
            if fc.get("hit_rate") is not None:
                line += f", fused cache {fc['hit_rate']:.1%} hit rate"
            print(line)
            if fus.get("flushes"):
                print("  flush reasons: "
                      + ", ".join(f"{k}: {v}" for k, v in
                                  sorted(fus["flushes"].items())))
            sites = fus.get("flush_sites") or {}
            if sites:
                # WHERE the fused program keeps being cut: the top
                # forcing sites across reasons (fuselint's runtime
                # cross-reference reads the same table)
                flat = sorted(
                    ((n, f"{site} ({reason})")
                     for reason, ss in sites.items()
                     for site, n in ss.items()),
                    reverse=True)[:5]
                print("  top flush sites: "
                      + ", ".join(f"{lbl}: {n}" for n, lbl in flat))
            if fus.get("fallbacks") or fus.get("demotions"):
                print(f"  degraded: {fus.get('fallbacks', 0)} fused "
                      f"fallbacks, {fus.get('demotions', 0)} ops learned "
                      "fusion-unsafe")
        comp = ds.get("compile") or {}
        if comp:
            # warm-start health: how much wall time XLA compilation cost
            # this process, how much the persistent disk cache absorbed,
            # and how long the first compiled step took to arrive
            line = (f"compile: {comp.get('fresh_compiles', 0)} fresh "
                    f"({comp.get('backend_compile_s', 0.0):.2f}s XLA), "
                    f"{comp.get('disk_cache_hits', 0)} loaded from disk "
                    f"cache")
            if comp.get("compile_time_saved_s"):
                line += (f" (~{comp['compile_time_saved_s']:.2f}s compile "
                         "saved)")
            if comp.get("cache_dir"):
                line += f" [{comp['cache_dir']}]"
            print(line)
            pre = (comp.get("precompiled_ops", 0)
                   + comp.get("precompiled_programs", 0))
            if pre:
                print(f"  warm-start precompiled: "
                      f"{comp.get('precompiled_ops', 0)} ops + "
                      f"{comp.get('precompiled_programs', 0)} programs "
                      f"from the shape manifest")
            tts = comp.get("time_to_first_step_s") or {}
            if tts:
                print("  time-to-first-step: "
                      + ", ".join(f"{k}: {v:.2f}s"
                                  for k, v in sorted(tts.items())))
            if op_detail and comp.get("per_op_compile_s"):
                top = sorted(comp["per_op_compile_s"].items(),
                             key=lambda kv: -kv[1])[:5]
                print("  compile-heavy ops: "
                      + ", ".join(f"{k}: {v:.2f}s" for k, v in top))
            if op_detail and comp.get("program_compile_s"):
                print("  whole-step programs: "
                      + ", ".join(f"{k}: {v:.2f}s" for k, v in
                                  sorted(comp["program_compile_s"].items())))
        uj = ds.get("unjittable")
        if uj and uj["total"]:
            print(f"unjittable ops: {uj['total']} "
                  f"({uj['manifest_preloaded']} manifest-preloaded, "
                  f"{uj['runtime_learned']} runtime-learned, "
                  f"{uj['decorated']} decorated)")
        fe = {k: v for k, v in ds.get("fault_events", {}).items() if v}
        if fe:
            # degradation is observable, not silent: any recovery path
            # that fired this run (save retry, restore fallback, rollback,
            # stall, eager demotion) shows up here
            print("fault events: "
                  + ", ".join(f"{k}: {v}" for k, v in sorted(fe.items())))
        self._telemetry_summary(op_detail)
        self._input_pipeline_summary()
        self._tracing_summary()
        if self._dir:
            print(f"trace artifacts: {self._dir}")

    @staticmethod
    def _telemetry_summary(op_detail):
        """One registry-backed section: the continuous-telemetry view
        (step-time distribution, per-op run attribution, export paths)
        that the snapshot sections above cannot provide."""
        from ..runtime import telemetry as _t

        if not _t.enabled():
            print("telemetry: disabled (PADDLE_TPU_TELEMETRY=0)")
            return
        snap = _t.snapshot()
        stream = _t.event_stream()
        parts = []
        steps = snap.get("paddle_tpu_train_steps_total")
        if steps and steps["series"]:
            parts.append(f"{int(steps['series'][0]['value'])} steps")
        hist = snap.get("paddle_tpu_step_seconds")
        if hist and hist["series"]:
            s = hist["series"][0]
            if s["count"]:
                parts.append(
                    f"step avg {s['sum'] / s['count'] * 1e3:.1f}ms")
        if stream is not None:
            parts.append(f"{stream.emitted} events -> {stream.path}")
        if not parts and not snap:
            return  # nothing registered and no stream: stay quiet
        print("telemetry: " + (", ".join(parts) if parts
                               else f"{len(snap)} metric families"))
        runh = snap.get("paddle_tpu_op_run_seconds")
        if op_detail and runh and runh["series"]:
            # sampled per-op RUN time (device-complete wall time), the
            # attribution dimension compile_s cannot see
            top = sorted(runh["series"],
                         key=lambda s: -(s["sum"] / s["count"]
                                         if s["count"] else 0.0))[:5]
            print("  run-time-heavy ops (sampled avg): "
                  + ", ".join(
                      f"{s['labels'].get('op')}: "
                      f"{s['sum'] / s['count'] * 1e3:.2f}ms"
                      for s in top if s["count"]))
        dw = snap.get("paddle_tpu_data_wait_seconds")
        if dw and dw["series"] and dw["series"][0]["count"]:
            # input-pipeline stall time (Model.fit times the loader's
            # next() per batch) — the visibility prerequisite for the
            # async-staging ROADMAP item
            s = dw["series"][0]
            print(f"  data wait: {s['sum']:.3f}s over {s['count']} "
                  f"batches (avg {s['sum'] / s['count'] * 1e3:.2f}ms)")

    @staticmethod
    def _input_pipeline_summary():
        """Async input pipeline (io/prefetch.py): prefetcher depth /
        stall / overlap counters plus the h2d histogram — the view
        that says whether the data path still costs step time."""
        pf, h2d = _input_pipeline_stats()
        if pf is None and h2d is None:
            return
        parts = []
        if pf is not None:
            parts.append(f"{pf['batches']} batches prefetched "
                         f"(depth {pf['depth']})")
            if pf["overlap_ratio"] is not None:
                parts.append(f"overlap {pf['overlap_ratio']:.1%}")
            if pf["stalls"]:
                parts.append(f"{pf['stalls']} stalls "
                             f"({pf['stall_s']:.3f}s)")
            for k, label in (("producer_deaths", "producer deaths"),
                             ("shard_fallbacks", "shard fallbacks")):
                if pf[k]:
                    parts.append(f"{pf[k]} {label}")
        if h2d is not None and h2d["count"]:
            parts.append(f"h2d {h2d['sum_s']:.3f}s over {h2d['count']} "
                         f"commits (avg "
                         f"{h2d['sum_s'] / h2d['count'] * 1e3:.2f}ms)")
        if parts:
            print("input pipeline: " + ", ".join(parts))

    @staticmethod
    def _tracing_summary():
        """Span-timeline section (runtime/tracing.py): per-phase totals
        and the top spans by self time, plus the trace file Perfetto
        loads. Silent when tracing never recorded anything."""
        from ..runtime import tracing as _tr

        for line in _tr.summary_lines():
            print(line)

    def export(self, path=None, format="json"):
        """The jax trace directory holds the exported artifacts."""
        return self._dir

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def export_chrome_tracing(dir_name, worker_name=None):
    """on_trace_ready factory (reference export_chrome_tracing): points the
    trace directory at dir_name."""
    os.makedirs(dir_name, exist_ok=True)

    def handler(prof):
        if prof._dir is None:
            prof._dir = dir_name
        return prof._dir

    return handler


def load_profiler_result(filename):
    raise NotImplementedError(
        "load back traces with TensorBoard/Perfetto from the trace dir")


class SortedKeys(enum.Enum):
    """Summary-table sort keys (reference: profiler_statistic.py:34). The
    device columns read TPU times from the jax trace."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


def export_protobuf(dir_name, worker_name=None):
    """on_trace_ready factory (reference profiler.py:205). The jax profiler
    already writes protobuf (.xplane.pb) into the trace directory, so this
    is export_chrome_tracing with the same destination contract."""
    return export_chrome_tracing(dir_name, worker_name)


__all__ += ["SortedKeys", "export_protobuf"]
