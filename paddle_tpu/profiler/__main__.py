"""``python -m paddle_tpu.profiler`` — the runtime summary as data.

Two modes:

* ``--json`` (default): print this process's `profiler.summary_dict()`
  as JSON — the machine-readable twin of `Profiler.summary()`. Useful
  at the end of a driver script (``import`` + run + ``-m`` in one
  interpreter via ``python -c``), or as the canonical schema sample
  for tooling.
* ``--statusz HOST:PORT [--route /statusz]``: fetch a route from a
  LIVE process's diagnostics introspection server
  (``PADDLE_TPU_STATUSZ=<port>``) and print it — external tooling's
  path to a running trainer/server without scraping printed text.
"""
from __future__ import annotations

import argparse
import json
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m paddle_tpu.profiler")
    ap.add_argument("--json", action="store_true", default=True,
                    help="print summary_dict() as JSON (default)")
    ap.add_argument("--indent", type=int, default=1)
    ap.add_argument("--statusz", metavar="HOST:PORT",
                    help="fetch from a live /statusz server instead of "
                         "summarizing this (fresh) process")
    ap.add_argument("--route", default="/statusz",
                    help="route to fetch with --statusz "
                         "(/statusz /metrics /stacks /flightrecorder "
                         "/serving)")
    args = ap.parse_args(argv)

    if args.statusz:
        import urllib.request

        url = f"http://{args.statusz}{args.route}"
        with urllib.request.urlopen(url, timeout=5) as r:
            body = r.read().decode("utf-8", "replace")
        try:
            # re-serialize so --indent applies uniformly
            print(json.dumps(json.loads(body), indent=args.indent,
                             default=str))
        except ValueError:  # text routes (/metrics, /healthz): as-is
            sys.stdout.write(body)
        return 0

    from . import summary_dict

    print(json.dumps(summary_dict(), indent=args.indent, default=str))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
