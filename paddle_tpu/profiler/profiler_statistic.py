"""paddle.profiler.profiler_statistic (reference: python/paddle/
profiler/profiler_statistic.py — the summary-table machinery).

The statistics engine here is the Profiler's own event store (host-side
RecordEvent spans + XLA cost analysis); this module restores the
reference import path: SortedKeys, StatisticData over the collected
events, and _build_table producing the reference-shaped summary text.
"""
from __future__ import annotations

from . import SortedKeys  # noqa: F401

__all__ = ["SortedKeys", "StatisticData"]


class StatisticData:
    """Aggregate view over a finished Profiler's collected events
    (reference profiler_statistic.py:589 wraps the C++ node trees; here
    the event store is already host-side)."""

    def __init__(self, events):
        self.events = list(events)

    def totals(self):
        """name -> (calls, total_ms, max_ms, min_ms)."""
        out = {}
        for e in self.events:
            name = getattr(e, "name", str(e))
            dur = float(getattr(e, "duration_ms", 0.0))
            cnt, tot, mx, mn = out.get(name, (0, 0.0, 0.0, float("inf")))
            out[name] = (cnt + 1, tot + dur, max(mx, dur), min(mn, dur))
        return out


def _build_table(statistic_data, sorted_by=None, op_detail=True,
                 thread_sep=False, time_unit="ms", row_limit=100,
                 max_src_column_width=75):
    """Reference-shaped text table of event totals, sorted per
    SortedKeys (total / avg / max / min — the CPU-side keys; there is
    no separate GPU timeline on this substrate)."""
    totals = statistic_data.totals()
    name_of = getattr(sorted_by, "name", "") or ""
    if "Max" in name_of:
        key = (lambda kv: -kv[1][2])
    elif "Min" in name_of:
        key = (lambda kv: kv[1][3])
    elif "Avg" in name_of:
        key = (lambda kv: -(kv[1][1] / max(kv[1][0], 1)))
    else:  # total time (the reference default)
        key = (lambda kv: -kv[1][1])
    rows = sorted(totals.items(), key=key)[:row_limit]
    width = max([len("Name")] + [len(n) for n, _ in rows]) + 2
    lines = [f"{'Name':<{width}}{'Calls':>8}{'Total(ms)':>12}"
             f"{'Max(ms)':>10}"]
    lines.append("-" * (width + 30))
    for name, (cnt, tot, mx, _mn) in rows:
        lines.append(f"{name:<{width}}{cnt:>8}{tot:>12.3f}{mx:>10.3f}")
    return "\n".join(lines)
