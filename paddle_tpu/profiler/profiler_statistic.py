"""paddle.profiler.profiler_statistic (reference: python/paddle/
profiler/profiler_statistic.py — the summary-table machinery).

The statistics engine here is the Profiler's own event store (host-side
RecordEvent spans + XLA cost analysis); this module restores the
reference import path: SortedKeys, StatisticData over the collected
events, and _build_table producing the reference-shaped summary text.
"""
from __future__ import annotations

from . import SortedKeys  # noqa: F401

__all__ = ["SortedKeys", "StatisticData"]


class StatisticData:
    """Aggregate view over a finished Profiler's collected events
    (reference profiler_statistic.py:589 wraps the C++ node trees; here
    the event store is already host-side)."""

    def __init__(self, events):
        self.events = list(events)

    def totals(self):
        out = {}
        for e in self.events:
            name = getattr(e, "name", str(e))
            dur = float(getattr(e, "duration_ms", 0.0))
            cnt, tot = out.get(name, (0, 0.0))
            out[name] = (cnt + 1, tot + dur)
        return out


def _build_table(statistic_data, sorted_by=None, op_detail=True,
                 thread_sep=False, time_unit="ms", row_limit=100,
                 max_src_column_width=75):
    """Reference-shaped text table of event totals."""
    totals = statistic_data.totals()
    key = (lambda kv: -kv[1][1])
    if sorted_by == SortedKeys.CPUMax:
        key = (lambda kv: -kv[1][1])
    rows = sorted(totals.items(), key=key)[:row_limit]
    width = max([len("Name")] + [len(n) for n, _ in rows]) + 2
    lines = [f"{'Name':<{width}}{'Calls':>8}{'Total(ms)':>12}"]
    lines.append("-" * (width + 20))
    for name, (cnt, tot) in rows:
        lines.append(f"{name:<{width}}{cnt:>8}{tot:>12.3f}")
    return "\n".join(lines)
