"""paddle.metric (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


def _np(x):
    return np.asarray(x._value) if isinstance(x, Tensor) else np.asarray(x)


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        p = _np(pred)
        l = _np(label)
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l[..., 0]
        topi = np.argsort(-p, axis=-1)[..., :self.maxk]
        correct = (topi == l[..., None])
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        num = c.shape[0] if c.ndim else 1
        for i, k in enumerate(self.topk):
            self.total[i] += c[..., :k].sum()
        self.count += num
        res = [self.total[i] / max(self.count, 1) for i in range(len(self.topk))]
        return res[0] if len(res) == 1 else res

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        res = [t / max(self.count, 1) for t in self.total]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = _np(preds).ravel()
        l = _np(labels).ravel()
        pred_pos = p > 0.5
        self.tp += int(np.sum(pred_pos & (l == 1)))
        self.fp += int(np.sum(pred_pos & (l == 0)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = _np(preds).ravel()
        l = _np(labels).ravel()
        pred_pos = p > 0.5
        self.tp += int(np.sum(pred_pos & (l == 1)))
        self.fn += int(np.sum(~pred_pos & (l == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc", *args,
                 **kwargs):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        l = _np(labels).ravel()
        if p.ndim == 2:
            p = p[:, 1]  # prob of positive class
        idx = np.minimum((p * self.num_thresholds).astype(int),
                         self.num_thresholds)
        n_bins = self.num_thresholds + 1
        pos_mask = l.astype(bool)
        self._stat_pos += np.bincount(idx[pos_mask], minlength=n_bins)
        self._stat_neg += np.bincount(idx[~pos_mask], minlength=n_bins)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # trapezoidal over thresholds (descending)
        pos = np.cumsum(self._stat_pos[::-1])
        neg = np.cumsum(self._stat_neg[::-1])
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") \
            else float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    """Top-k accuracy as a TRACED op: numpy here would concretize at
    static-program build time and bake the dummy-feed result into the
    replayed computation (it fetched garbage; caught by the fluid-era
    example)."""
    from .. import tensor as T

    lab = label
    if lab.ndim < input.ndim:
        lab = T.unsqueeze(lab, -1)
    _, topi = T.topk(input, k, axis=-1)
    hit = T.equal(T.cast(topi, "int64"), T.cast(lab, "int64"))
    return T.mean(T.cast(T.any(hit, axis=-1), "float32"))
