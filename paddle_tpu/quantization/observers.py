"""Calibration observers — collect activation/weight ranges for PTQ/QAT.

Reference: python/paddle/fluid/contrib/slim/quantization/imperative/
ptq_quantizer.py:1 (AbsmaxQuantizer, HistQuantizer, KLQuantizer,
PerChannelAbsmaxQuantizer) and quantization_pass.py:1 (abs_max /
moving_average_abs_max / channel_wise_abs_max strategies).

TPU-native: the stat reduction (max|x|, histogram) runs on-device as a
jit-cached XLA reduction during the calibration sweep; only the scalar
result crosses to the host. Scales are plain numpy on the host — they are
compile-time constants of the quantized program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["AbsmaxObserver", "MovingAverageAbsmaxObserver",
           "PerChannelAbsmaxObserver", "HistObserver", "build_observer"]


@jax.jit
def _absmax(x):
    return jnp.max(jnp.abs(x.astype(jnp.float32)))


def _absmax_axis(x, axis):
    red = tuple(i for i in range(x.ndim) if i != axis)
    return jnp.max(jnp.abs(x.astype(jnp.float32)), axis=red)


class AbsmaxObserver:
    """scale = max |x| over every calibration batch."""

    def __init__(self, bits=8):
        self.bits = bits
        self._max = 0.0

    def update(self, value):
        self._max = max(self._max, float(_absmax(value)))

    def scale(self):
        return np.float32(max(self._max, 1e-8))


class MovingAverageAbsmaxObserver:
    """scale = EMA of per-batch max |x| (reference moving_average_abs_max,
    quantization_pass.py:1 — state update folded into the eval sweep)."""

    def __init__(self, bits=8, moving_rate=0.9):
        self.bits = bits
        self.rate = moving_rate
        self._state = None

    def update(self, value):
        m = float(_absmax(value))
        self._state = m if self._state is None else \
            self.rate * self._state + (1.0 - self.rate) * m

    def scale(self):
        return np.float32(max(self._state or 0.0, 1e-8))


class PerChannelAbsmaxObserver:
    """Per-output-channel |w|max (reference channel_wise_abs_max)."""

    def __init__(self, bits=8, axis=-1):
        self.bits = bits
        self.axis = axis
        self._max = None

    def update(self, value):
        m = np.asarray(_absmax_axis(jnp.asarray(value),
                                    self.axis % value.ndim))
        self._max = m if self._max is None else np.maximum(self._max, m)

    def scale(self):
        return np.maximum(self._max, 1e-8).astype(np.float32)


class HistObserver:
    """Percentile-of-histogram scale (reference HistQuantizer /
    hist_percent; the KL algo of post_training_quantization.py:115 selects
    a threshold from the same histogram — `algo="KL"` maps here with the
    percentile criterion, documented TPU-native simplification)."""

    def __init__(self, bits=8, bins=2048, percent=0.99999):
        self.bits = bits
        self.bins = bins
        self.percent = percent
        self._hist = None
        self._edge = None

    def update(self, value):
        v = np.abs(np.asarray(jax.device_get(value), np.float32)).ravel()
        top = float(v.max()) if v.size else 0.0
        if top <= 0.0:
            return
        if self._hist is None:
            self._edge = max(top, 1e-8)
            self._hist, _ = np.histogram(v, bins=self.bins,
                                         range=(0.0, self._edge))
            return
        if top > self._edge:  # re-bin the old histogram onto a wider range
            ratio = top / self._edge
            idx = np.minimum(
                (np.arange(self.bins) * (1.0 / ratio)).astype(np.int64),
                self.bins - 1)
            new = np.zeros(self.bins, np.int64)
            np.add.at(new, idx, self._hist)
            self._hist = new
            self._edge = top
        h, _ = np.histogram(v, bins=self.bins, range=(0.0, self._edge))
        self._hist = self._hist + h

    def scale(self):
        if self._hist is None:
            return np.float32(1e-8)
        cdf = np.cumsum(self._hist) / max(self._hist.sum(), 1)
        k = int(np.searchsorted(cdf, self.percent))
        k = min(k, self.bins - 1)
        return np.float32(max((k + 1) * self._edge / self.bins, 1e-8))


def build_observer(kind, bits=8, **kw):
    kind = (kind or "abs_max").lower()
    if kind in ("abs_max", "absmax", "range_abs_max"):
        return AbsmaxObserver(bits)
    if kind in ("moving_average_abs_max", "ema"):
        return MovingAverageAbsmaxObserver(bits, kw.get("moving_rate", 0.9))
    if kind in ("channel_wise_abs_max", "per_channel"):
        return PerChannelAbsmaxObserver(bits, kw.get("axis", -1))
    if kind in ("hist", "kl", "hist_percent"):
        return HistObserver(bits, percent=kw.get("hist_percent", 0.99999))
    raise ValueError(f"unknown observer kind {kind!r}")
