"""paddle.quantization — PTQ + QAT for the TPU int8 path.

Reference:
- python/paddle/fluid/contrib/slim/quantization/post_training_quantization.py:97
  (PostTrainingQuantization: calibrate over a data loader, pick scales by
  abs_max/hist/KL, rewrite matmul/conv to int8)
- python/paddle/fluid/contrib/slim/quantization/imperative/ptq.py:40
  (ImperativePTQ.quantize / save_quantized_model)
- python/paddle/fluid/contrib/slim/quantization/imperative/qat.py:42
  (ImperativeQuantAware — fake-quant QAT wrappers)

TPU-native design: the reference mutates its static ProgramDesc graph with
quantize/dequantize ops; here quantization happens at the LAYER level before
XLA tracing — calibration observers ride a jitted eval sweep, then
quantizable layers are swapped for int8 layers whose dot/conv lower to XLA
integer dot_general (MXU int8). The XLA graph itself is never mutated; the
rewritten model re-traces to an int8 HLO program.
"""
from __future__ import annotations

import numpy as np

from ..nn.layer.layers import Layer
from .layers import (  # noqa: F401
    QATConv2D, QATLinear, QuantizedConv2D, QuantizedLinear, fake_quant,
    quantize_weight,
)
from .observers import (  # noqa: F401
    AbsmaxObserver, HistObserver, MovingAverageAbsmaxObserver,
    PerChannelAbsmaxObserver, build_observer,
)

__all__ = ["QuantConfig", "ImperativePTQ", "ImperativeQuantAware",
           "PostTrainingQuantization", "QuantizedLinear", "QuantizedConv2D",
           "QATLinear", "QATConv2D", "fake_quant", "quantize_weight",
           "AbsmaxObserver", "MovingAverageAbsmaxObserver",
           "PerChannelAbsmaxObserver", "HistObserver", "build_observer"]


class QuantConfig:
    """Reference imperative/ptq_config.py PTQConfig — which observers and
    bit widths to use."""

    def __init__(self, activation_quantize_type="abs_max",
                 weight_quantize_type="channel_wise_abs_max",
                 activation_bits=8, weight_bits=8, moving_rate=0.9,
                 hist_percent=0.99999):
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        self.activation_bits = activation_bits
        self.weight_bits = weight_bits
        self.moving_rate = moving_rate
        self.hist_percent = hist_percent


def _quantizable(layer):
    from .. import nn

    if isinstance(layer, nn.Linear):
        return "linear"
    if isinstance(layer, nn.Conv2D):
        return "conv2d"
    return None


def _walk_replace(root, fn):
    """Replace children for which fn(child) returns a new layer."""
    for parent in root.sublayers(include_self=True):
        for k, child in list(parent._sub_layers.items()):
            new = fn(child)
            if new is not None and new is not child:
                parent._sub_layers[k] = new


class _Observation:
    def __init__(self, observer):
        self.observer = observer


class ImperativePTQ:
    """Post-training quantization for dygraph models.

    ptq = ImperativePTQ(QuantConfig()); ptq.quantize(model)
    ... run calibration forwards (jitted eval sweep) ...
    ptq.convert(model)  ->  int8 layers in place
    """

    def __init__(self, quant_config=None):
        self.cfg = quant_config or QuantConfig()
        self._hooks = []

    def quantize(self, model, inplace=True):
        cfg = self.cfg
        for name, layer in model.named_sublayers(include_self=True):
            kind = _quantizable(layer)
            if kind is None:
                continue
            obs = build_observer(cfg.activation_quantize_type,
                                 cfg.activation_bits,
                                 moving_rate=cfg.moving_rate,
                                 hist_percent=cfg.hist_percent)
            layer._ptq_observation = _Observation(obs)
            # observe the layer INPUT (the activation that will be
            # quantized at inference): forward pre hook
            handle = layer.register_forward_pre_hook(
                lambda l, inp, _o=obs: _o.update(inp[0]._value))
            self._hooks.append(handle)
        return model

    def convert(self, model, inplace=True):
        """Swap calibrated layers for int8 layers. Returns the converted
        model — when `model` ITSELF is a quantizable leaf (bare nn.Linear)
        the returned object is the replacement, so always use the return
        value."""
        cfg = self.cfg
        for h in self._hooks:
            h.remove()
        self._hooks = []

        def _swap(child):
            obs = getattr(child, "_ptq_observation", None)
            if obs is None:
                return None
            kind = _quantizable(child)
            scale = obs.observer.scale()
            scale = float(np.max(scale))  # activation scale is per-tensor
            if kind == "linear":
                return QuantizedLinear(child, scale, cfg.weight_bits,
                                       cfg.activation_bits)
            if kind == "conv2d":
                return QuantizedConv2D(child, scale, cfg.weight_bits,
                                       cfg.activation_bits)
            return None

        root = _swap(model)
        if root is not None:
            return root
        _walk_replace(model, _swap)
        return model

    def save_quantized_model(self, model, path, input_spec=None):
        from .. import jit

        self.convert(model)
        return jit.save(model, path, input_spec=input_spec)


class ImperativeQuantAware:
    """Quantization-aware training (reference imperative/qat.py:42).

    imperative_qat.quantize(model): swaps Linear/Conv2D for fake-quant
    wrappers (straight-through estimator). After training,
    convert(model) produces real int8 layers using the QAT-observed
    activation scales.
    """

    def __init__(self, quantizable_layer_type=("Conv2D", "Linear"),
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_bits=8, activation_bits=8, moving_rate=0.9,
                 **unused):
        self.types = set(quantizable_layer_type)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate

    def quantize(self, model):
        """Swap quantizable layers for fake-quant wrappers. Returns the
        wrapped model — when `model` itself is a bare Linear/Conv2D the
        wrapper is the return value, so always use it."""
        def _swap(child):
            kind = _quantizable(child)
            if kind == "linear" and "Linear" in self.types:
                return QATLinear(child, self.weight_bits,
                                 self.activation_bits, self.moving_rate)
            if kind == "conv2d" and "Conv2D" in self.types:
                return QATConv2D(child, self.weight_bits,
                                 self.activation_bits, self.moving_rate)
            return None

        root = _swap(model)
        if root is not None:
            return root
        _walk_replace(model, _swap)
        return model

    def convert(self, model):
        def _swap(child):
            if isinstance(child, QATLinear):
                return QuantizedLinear(child.inner,
                                       child.observed_act_scale(),
                                       self.weight_bits,
                                       self.activation_bits)
            if isinstance(child, QATConv2D):
                return QuantizedConv2D(child.inner,
                                       child.observed_act_scale(),
                                       self.weight_bits,
                                       self.activation_bits)
            return None

        root = _swap(model)
        if root is not None:
            return root
        _walk_replace(model, _swap)
        return model

    def save_quantized_model(self, layer, path, input_spec=None):
        from .. import jit

        self.convert(layer)
        return jit.save(layer, path, input_spec=input_spec)


class PostTrainingQuantization:
    """Reference post_training_quantization.py:97, reshaped for the layer
    world: feed a dygraph model + data loader instead of a saved static
    program (the XLA graph cannot be mutated post-hoc; the rewritten model
    re-traces to int8 HLO). algo: abs_max | avg | hist | KL | mse.
    """

    def __init__(self, executor=None, model=None, data_loader=None,
                 sample_generator=None, batch_generator=None, scope=None,
                 model_dir=None, model_filename=None, params_filename=None,
                 batch_size=10, batch_nums=None, algo="hist",
                 hist_percent=0.99999,
                 quantizable_op_type=("conv2d", "mul", "matmul"),
                 is_full_quantize=False, activation_bits=8, weight_bits=8,
                 activation_quantize_type=None,
                 weight_quantize_type="channel_wise_abs_max",
                 onnx_format=False, **unused):
        if model is None:
            raise ValueError(
                "PostTrainingQuantization on paddle_tpu takes the dygraph "
                "`model=` directly (static program mutation does not exist "
                "on the XLA path; see module docstring)")
        if data_loader is None:
            raise ValueError("data_loader is required for calibration")
        algo = {"kl": "hist", "avg": "moving_average_abs_max",
                "abs_max": "abs_max", "hist": "hist",
                "mse": "hist"}.get(str(algo).lower(), "abs_max")
        self.model = model
        self.loader = data_loader
        self.batch_nums = batch_nums
        self.cfg = QuantConfig(
            activation_quantize_type=activation_quantize_type or algo,
            weight_quantize_type=weight_quantize_type,
            activation_bits=activation_bits, weight_bits=weight_bits,
            hist_percent=hist_percent)
        self._ptq = ImperativePTQ(self.cfg)

    def quantize(self):
        from ..core.autograd import no_grad

        self._ptq.quantize(self.model)
        self.model.eval()
        with no_grad():
            for i, batch in enumerate(self.loader):
                xs = batch[0] if isinstance(batch, (list, tuple)) else batch
                self.model(xs)
                if self.batch_nums and i + 1 >= self.batch_nums:
                    break
        self.model = self._ptq.convert(self.model)
        return self.model

    def save_quantized_model(self, save_model_path, model_filename=None,
                             params_filename=None):
        from .. import jit

        return jit.save(self.model, save_model_path)
