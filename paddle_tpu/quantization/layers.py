"""Quantized / fake-quantized layers.

Reference: python/paddle/fluid/contrib/slim/quantization/imperative/qat.py:1
(QuantizedConv2D, QuantizedLinear with FakeQuantAbsMax wrappers) and
quantization_pass.py:1 (quantize_dequantize op rewrites).

TPU-native: real int8 execution maps onto XLA's integer dot_general /
convolution with `preferred_element_type=int32` — the MXU's native int8
path on TPU (the reference instead relies on cuDNN/MKLDNN int8 kernels).
Fake-quant (QAT) uses the straight-through estimator expressed as
`x + stop_gradient(qdq(x) - x)`, which XLA fuses into the surrounding
computation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply
from ..nn.layer.layers import Layer

__all__ = ["QuantizedLinear", "QuantizedConv2D", "QATLinear", "QATConv2D",
           "quantize_weight", "fake_quant"]


def _qmax(bits):
    return float(2 ** (bits - 1) - 1)


def quantize_weight(w, bits=8, channel_axis=None):
    """float weight -> (int8 array, float scale). Per-channel when
    channel_axis is given (reference channel_wise_abs_max)."""
    w = np.asarray(jax.device_get(w), np.float32)
    qm = _qmax(bits)
    if channel_axis is None:
        scale = max(float(np.abs(w).max()), 1e-8) / qm
    else:
        red = tuple(i for i in range(w.ndim) if i != channel_axis % w.ndim)
        scale = np.maximum(np.abs(w).max(axis=red), 1e-8) / qm
        shape = [1] * w.ndim
        shape[channel_axis % w.ndim] = -1
        scale = scale.reshape(shape)
    q = np.clip(np.round(w / scale), -qm - 1, qm).astype(np.int8)
    return q, np.asarray(scale, np.float32)


def fake_quant(x, scale, bits=8):
    """Quantize-dequantize with straight-through gradients (tape op)."""
    qm = _qmax(bits)

    def _qdq(v, s):
        s = jnp.maximum(s, 1e-8) / qm
        qdq = jnp.clip(jnp.round(v / s), -qm - 1, qm) * s
        return v + jax.lax.stop_gradient(qdq - v)

    return apply(_qdq, x, scale)


def _int8_matmul(xv, w_q, w_scale, a_scale, bits):
    """[.., in] @ int8[in, out] with int32 accumulation on the MXU."""
    qm = _qmax(bits)
    inv = qm / jnp.maximum(a_scale, 1e-8)
    x_q = jnp.clip(jnp.round(xv.astype(jnp.float32) * inv),
                   -qm - 1, qm).astype(jnp.int8)
    acc = jax.lax.dot_general(
        x_q, w_q, (((x_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out_scale = (a_scale / qm) * w_scale.reshape(-1)  # [out]
    return acc.astype(jnp.float32) * out_scale


class QuantizedLinear(Layer):
    """Int8 inference Linear (weight int8 per-out-channel, activation scale
    from calibration). Reference imperative/qat.py QuantizedLinear."""

    def __init__(self, linear, act_scale, weight_bits=8, act_bits=8):
        super().__init__()
        self.bits = weight_bits
        self.act_bits = act_bits
        w_q, w_scale = quantize_weight(linear.weight._value, weight_bits,
                                       channel_axis=1)  # [in, out]
        self._w_q = jnp.asarray(w_q)
        self._w_scale = jnp.asarray(w_scale)
        self._a_scale = jnp.float32(float(np.asarray(act_scale)))
        self.bias = getattr(linear, "bias", None)
        self.name = getattr(linear, "name", None)

    def forward(self, x):
        out = apply(lambda v: _int8_matmul(v, self._w_q, self._w_scale,
                                           self._a_scale, self.act_bits), x)
        if self.bias is not None:
            out = out + self.bias
        return out


class QuantizedConv2D(Layer):
    """Int8 inference Conv2D: integer convolution, int32 accumulation.
    Reference imperative/qat.py QuantizedConv2D."""

    def __init__(self, conv, act_scale, weight_bits=8, act_bits=8):
        super().__init__()
        self.act_bits = act_bits
        w_q, w_scale = quantize_weight(conv.weight._value, weight_bits,
                                       channel_axis=0)  # [out, in, kh, kw]
        self._w_q = jnp.asarray(w_q)
        self._w_scale = jnp.asarray(w_scale)  # [out,1,1,1]
        self._a_scale = jnp.float32(float(np.asarray(act_scale)))
        self.bias = getattr(conv, "bias", None)
        self._stride = conv._stride
        self._padding = conv._padding
        self._dilation = conv._dilation
        self._groups = conv._groups
        self._data_format = conv._data_format

    def forward(self, x):
        from ..nn.functional.conv import _norm_padding, _norm_tuple

        qm = _qmax(self.act_bits)
        stride = _norm_tuple(self._stride, 2)
        dilation = _norm_tuple(self._dilation, 2)
        pad = _norm_padding(self._padding, 2)
        groups = self._groups
        channel_last = self._data_format == "NHWC"
        lhs_spec = "NHWC" if channel_last else "NCHW"
        dn = jax.lax.conv_dimension_numbers(
            (1, 1, 1, 1), (1, 1, 1, 1), (lhs_spec, "OIHW", lhs_spec))
        ch_shape = (1, 1, 1, -1) if channel_last else (1, -1, 1, 1)

        def _q_conv(v):
            inv = qm / jnp.maximum(self._a_scale, 1e-8)
            x_q = jnp.clip(jnp.round(v.astype(jnp.float32) * inv),
                           -qm - 1, qm).astype(jnp.int8)
            acc = jax.lax.conv_general_dilated(
                x_q, self._w_q, window_strides=stride, padding=pad,
                rhs_dilation=dilation, dimension_numbers=dn,
                feature_group_count=groups,
                preferred_element_type=jnp.int32)
            scale = (self._a_scale / qm) * self._w_scale.reshape(ch_shape)
            return acc.astype(jnp.float32) * scale

        out = apply(_q_conv, x)
        if self.bias is not None:
            out = out + self.bias.reshape(list(ch_shape))
        return out


class _QATBase(Layer):
    """Fake-quant training wrapper: weight abs-max fake-quant + activation
    EMA fake-quant, straight-through gradients (reference qat.py
    FakeQuantAbsMax/FakeQuantMovingAverageAbsMax)."""

    def __init__(self, layer, weight_bits=8, act_bits=8, moving_rate=0.9):
        super().__init__()
        self.inner = layer
        self.weight_bits = weight_bits
        self.act_bits = act_bits
        self.rate = moving_rate
        self._act_state = None  # python float EMA, updated eagerly

    def _act_scale(self, x):
        if self.training and not isinstance(x._value, jax.core.Tracer):
            m = float(jnp.max(jnp.abs(x._value.astype(jnp.float32))))
            self._act_state = m if self._act_state is None else \
                self.rate * self._act_state + (1 - self.rate) * m
        return jnp.float32(max(self._act_state or 1.0, 1e-8))

    def observed_act_scale(self):
        return np.float32(max(self._act_state or 1.0, 1e-8))


class QATLinear(_QATBase):
    def forward(self, x):
        w = fake_quant(self.inner.weight,
                       jnp.max(jnp.abs(self.inner.weight._value)),
                       self.weight_bits)
        x = fake_quant(x, self._act_scale(x), self.act_bits)
        out = x @ w
        if self.inner.bias is not None:
            out = out + self.inner.bias
        return out


class QATConv2D(_QATBase):
    def forward(self, x):
        from ..nn import functional as F

        w = fake_quant(self.inner.weight,
                       jnp.max(jnp.abs(self.inner.weight._value)),
                       self.weight_bits)
        x = fake_quant(x, self._act_scale(x), self.act_bits)
        c = self.inner
        return F.conv2d(x, w, c.bias, c._stride, c._padding, c._dilation,
                        c._groups, c._data_format)
