"""Beta distribution.

Reference: python/paddle/distribution/beta.py (Beta(alpha, beta) as an
ExponentialFamily).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import betaln, gammaln

from .distribution import _param, _value, _wrap
from .exponential_family import ExponentialFamily

__all__ = ["Beta"]


class Beta(ExponentialFamily):
    def __init__(self, alpha, beta):
        self.alpha = _param(alpha)
        self.beta = _param(beta)
        b = jnp.broadcast_shapes(self.alpha.shape, self.beta.shape)
        super().__init__(batch_shape=b)

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(
            self.alpha / (self.alpha + self.beta), self.batch_shape))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return _wrap(jnp.broadcast_to(
            self.alpha * self.beta / (s ** 2 * (s + 1)), self.batch_shape))

    def log_prob(self, value):
        v = _value(value)
        return _wrap((self.alpha - 1) * jnp.log(v)
                     + (self.beta - 1) * jnp.log1p(-v)
                     - betaln(self.alpha, self.beta))

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        out = self._extend_shape(shape)
        return _wrap(jax.random.beta(self._key(), self.alpha, self.beta, out))

    def entropy(self):
        a, b = self.alpha, self.beta
        dg = jax.scipy.special.digamma
        h = (betaln(a, b) - (a - 1) * dg(a) - (b - 1) * dg(b)
             + (a + b - 2) * dg(a + b))
        return _wrap(jnp.broadcast_to(h, self.batch_shape))

    @property
    def _natural_parameters(self):
        return (self.alpha, self.beta)

    def _log_normalizer(self, x, y):
        return gammaln(x) + gammaln(y) - gammaln(x + y)

    @property
    def _mean_carrier_measure(self):
        # E[log h(x)] for h(x) = 1/(x(1-x)) under natural params (α, β)
        dg = jax.scipy.special.digamma
        a, b = self.alpha, self.beta
        return 2 * dg(a + b) - dg(a) - dg(b)
