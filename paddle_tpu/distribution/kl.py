"""KL divergence dispatch.

Reference: python/paddle/distribution/kl.py (register_kl decorator with
most-derived-match dispatch; _kl_expfamily_expfamily via Bregman divergence).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .beta import Beta
from .categorical import Categorical
from .dirichlet import Dirichlet
from .distribution import _wrap
from .exponential_family import ExponentialFamily
from .normal import Normal
from .uniform import Uniform

__all__ = ["kl_divergence", "register_kl"]

_REGISTRY = {}


def register_kl(cls_p, cls_q):
    def decorator(fn):
        _REGISTRY[(cls_p, cls_q)] = fn
        return fn

    return decorator


def _dispatch(cls_p, cls_q):
    matches = [(p, q) for (p, q) in _REGISTRY
               if issubclass(cls_p, p) and issubclass(cls_q, q)]
    if not matches:
        raise NotImplementedError(
            f"no KL registered between {cls_p.__name__} and {cls_q.__name__}")

    def total_order(pair):
        # most-derived match wins: fewer MRO hops = better
        return (cls_p.__mro__.index(pair[0]), cls_q.__mro__.index(pair[1]))

    return _REGISTRY[min(matches, key=total_order)]


def kl_divergence(p, q):
    return _dispatch(type(p), type(q))(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    return p.kl_divergence(q)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    # KL finite only when support(p) ⊆ support(q)
    ratio = (q.high - q.low) / (p.high - p.low)
    inside = (q.low <= p.low) & (p.high <= q.high)
    return _wrap(jnp.where(inside, jnp.log(ratio), jnp.inf))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    return p.kl_divergence(q)


@register_kl(ExponentialFamily, ExponentialFamily)
def _kl_expfamily_expfamily(p, q):
    """KL(p||q) = A_q(θ_q) − A_p(θ_p) − ⟨θ_q − θ_p, ∇A_p(θ_p)⟩ for a shared
    sufficient statistic — gradients via jax.grad on the log normalizers."""
    if type(p) is not type(q):
        raise NotImplementedError(
            "exponential-family KL requires matching families")
    p_nat = [jnp.asarray(t) for t in p._natural_parameters]
    q_nat = [jnp.asarray(t) for t in q._natural_parameters]
    p_nat = [jnp.broadcast_to(a, jnp.broadcast_shapes(a.shape, b.shape))
             for a, b in zip(p_nat, q_nat)]
    q_nat = [jnp.broadcast_to(b, a.shape) for a, b in zip(p_nat, q_nat)]

    grads = jax.grad(lambda *ps: p._log_normalizer(*ps).sum(),
                     argnums=tuple(range(len(p_nat))))(*p_nat)
    kl = q._log_normalizer(*q_nat) - p._log_normalizer(*p_nat)
    for pp, qq, g in zip(p_nat, q_nat, grads):
        term = (pp - qq) * g
        # event-axis parameters (e.g. Dirichlet concentration) reduce over
        # the event axis; scalar-parameter families don't
        if term.ndim > kl.ndim:
            term = term.sum(tuple(range(kl.ndim, term.ndim)))
        kl = kl + term
    return _wrap(kl)


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    return _kl_expfamily_expfamily(p, q)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    return _kl_expfamily_expfamily(p, q)
