"""Categorical distribution.

Reference: python/paddle/distribution/categorical.py (Categorical(logits)
where `logits` are unnormalized probabilities — normalized by their sum, not
softmax, matching the reference semantics).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _param, _value, _wrap

__all__ = ["Categorical"]


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _param(logits)
        super().__init__(batch_shape=self.logits.shape[:-1])

    @property
    def _probs(self):
        return self.logits / self.logits.sum(-1, keepdims=True)

    def sample(self, shape=()):
        shape = tuple(shape) if not isinstance(shape, int) else (shape,)
        n = 1
        for s in shape:
            n *= s
        logp = jnp.log(self._probs)
        draws = jax.random.categorical(self._key(), logp, axis=-1,
                                       shape=(n,) + self.batch_shape)
        return _wrap(draws.reshape(shape + self.batch_shape))

    def entropy(self):
        p = self._probs
        logp = jnp.log(jnp.where(p > 0, p, 1.0))
        return _wrap(-(p * logp).sum(-1))

    def probs(self, value):
        v = _value(value).astype(jnp.int32)
        # broadcast so sample dims on `value` (e.g. scoring d.sample((n,)))
        # line up with the batch dims of the parameters
        p = jnp.broadcast_to(self._probs, v.shape + self._probs.shape[-1:])
        return _wrap(jnp.take_along_axis(p, v[..., None], axis=-1)
                     .squeeze(-1))

    def log_prob(self, value):
        return _wrap(jnp.log(self.probs(value)._value))

    def kl_divergence(self, other):
        p = self._probs
        q = other._probs
        logp = jnp.log(jnp.where(p > 0, p, 1.0))
        logq = jnp.log(q)
        return _wrap((p * (logp - logq)).sum(-1))
