"""Normal distribution.

Reference: python/paddle/distribution/normal.py:30 (Normal(loc, scale) with
sample/entropy/log_prob/probs/kl_divergence).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _param, _value, _wrap

__all__ = ["Normal"]

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _param(loc)
        self.scale = _param(scale)
        b = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        super().__init__(batch_shape=b)

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    @property
    def stddev(self):
        return _wrap(jnp.broadcast_to(self.scale, self.batch_shape))

    def sample(self, shape=(), seed=0):
        return self.rsample(shape)

    def rsample(self, shape=()):
        out = self._extend_shape(shape)
        eps = jax.random.normal(self._key(), out, self.loc.dtype)
        return _wrap(self.loc + self.scale * eps)

    def entropy(self):
        h = 0.5 + _HALF_LOG_2PI + jnp.log(self.scale)
        return _wrap(jnp.broadcast_to(h, self.batch_shape))

    def log_prob(self, value):
        v = _value(value)
        var = self.scale ** 2
        return _wrap(-((v - self.loc) ** 2) / (2 * var)
                     - jnp.log(self.scale) - _HALF_LOG_2PI)

    def cdf(self, value):
        v = _value(value)
        return _wrap(0.5 * (1 + jax.scipy.special.erf(
            (v - self.loc) / (self.scale * math.sqrt(2.0)))))

    def icdf(self, value):
        v = _value(value)
        return _wrap(self.loc + self.scale * math.sqrt(2.0)
                     * jax.scipy.special.erfinv(2 * v - 1))

    def kl_divergence(self, other):
        if isinstance(other, Normal):
            var_ratio = (self.scale / other.scale) ** 2
            t1 = ((self.loc - other.loc) / other.scale) ** 2
            return _wrap(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
        return super().kl_divergence(other)
