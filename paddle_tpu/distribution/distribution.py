"""Distribution base class.

Reference: python/paddle/distribution/distribution.py:40 (Distribution with
batch_shape/event_shape, sample/entropy/log_prob/probs/kl_divergence).
TPU-native design: parameters are held as jnp arrays; every method is a pure
jnp computation (jit/vmap/grad-compatible), sampling draws a subkey from the
functional PRNG store (framework/random.py) so it is reproducible under
paddle.seed and traceable under a key_scope.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..framework import random as rnd

__all__ = ["Distribution"]


def _param(x, dtype=None):
    """Coerce a ctor argument (Tensor | ndarray | scalar | list) to jnp."""
    if isinstance(x, Tensor):
        v = x._value
    else:
        v = jnp.asarray(x)
    if jnp.issubdtype(v.dtype, jnp.integer) or v.dtype == jnp.bool_:
        v = v.astype(dtype or jnp.float32)
    elif dtype is not None:
        v = v.astype(dtype)
    return v


def _value(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap(v):
    return Tensor(v)


def _sum_rightmost(x, n):
    """Reduce the trailing `n` axes (event-axis reduction helper)."""
    return x.sum(tuple(range(x.ndim - n, x.ndim))) if n > 0 else x


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(
            batch_shape.shape if isinstance(batch_shape, Tensor)
            else batch_shape)
        self._event_shape = tuple(
            event_shape.shape if isinstance(event_shape, Tensor)
            else event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        """Probability density/mass at `value` (exp of log_prob by default)."""
        return _wrap(jnp.exp(self.log_prob(value)._value))

    def probs(self, value):
        return self.prob(value)

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)

    # ---- helpers ---------------------------------------------------------
    def _extend_shape(self, sample_shape):
        if isinstance(sample_shape, Tensor):
            sample_shape = tuple(int(s) for s in np.asarray(sample_shape._value))
        return tuple(sample_shape) + self.batch_shape + self.event_shape

    @staticmethod
    def _key():
        return rnd.next_key()
