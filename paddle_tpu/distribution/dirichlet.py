"""Dirichlet distribution.

Reference: python/paddle/distribution/dirichlet.py (Dirichlet(concentration)
as an ExponentialFamily; event_shape is the trailing axis).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import digamma, gammaln

from .distribution import _param, _value, _wrap
from .exponential_family import ExponentialFamily

__all__ = ["Dirichlet"]


class Dirichlet(ExponentialFamily):
    def __init__(self, concentration):
        self.concentration = _param(concentration)
        if self.concentration.ndim < 1:
            raise ValueError(
                "concentration must be at least one-dimensional")
        super().__init__(batch_shape=self.concentration.shape[:-1],
                         event_shape=self.concentration.shape[-1:])

    @property
    def mean(self):
        return _wrap(self.concentration
                     / self.concentration.sum(-1, keepdims=True))

    @property
    def variance(self):
        a = self.concentration
        a0 = a.sum(-1, keepdims=True)
        return _wrap(a * (a0 - a) / (a0 ** 2 * (a0 + 1)))

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shape = tuple(shape)
        out = shape + self.batch_shape
        return _wrap(jax.random.dirichlet(self._key(), self.concentration,
                                          out))

    def log_prob(self, value):
        v = _value(value)
        a = self.concentration
        return _wrap(((a - 1) * jnp.log(v)).sum(-1)
                     + gammaln(a.sum(-1)) - gammaln(a).sum(-1))

    def entropy(self):
        a = self.concentration
        a0 = a.sum(-1)
        k = a.shape[-1]
        log_b = gammaln(a).sum(-1) - gammaln(a0)
        return _wrap(log_b + (a0 - k) * digamma(a0)
                     - ((a - 1) * digamma(a)).sum(-1))

    @property
    def _natural_parameters(self):
        return (self.concentration,)

    def _log_normalizer(self, x):
        return gammaln(x).sum(-1) - gammaln(x.sum(-1))

    @property
    def _mean_carrier_measure(self):
        # E[log h(x)] for h(x) = ∏ 1/x_i under natural params α
        a = self.concentration
        a0 = a.sum(-1)
        return (digamma(a0)[..., None] - digamma(a)).sum(-1)
