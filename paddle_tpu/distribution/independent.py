"""Independent: reinterpret batch dims of a base distribution as event dims.

Reference: python/paddle/distribution/independent.py.
"""
from __future__ import annotations

from .distribution import Distribution, _sum_rightmost, _wrap

__all__ = ["Independent"]


class Independent(Distribution):
    def __init__(self, base, reinterpreted_batch_rank):
        if not (0 < reinterpreted_batch_rank <= len(base.batch_shape)):
            raise ValueError(
                "reinterpreted_batch_rank must be in (0, len(batch_shape)]")
        self._base = base
        self._reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        shape = base.batch_shape + base.event_shape
        n_event = len(base.event_shape) + self._reinterpreted_batch_rank
        super().__init__(batch_shape=shape[:len(shape) - n_event],
                         event_shape=shape[len(shape) - n_event:])

    @property
    def mean(self):
        return self._base.mean

    @property
    def variance(self):
        return self._base.variance

    def sample(self, shape=()):
        return self._base.sample(shape)

    def rsample(self, shape=()):
        return self._base.rsample(shape)

    def log_prob(self, value):
        return _wrap(_sum_rightmost(self._base.log_prob(value)._value,
                                    self._reinterpreted_batch_rank))

    def entropy(self):
        return _wrap(_sum_rightmost(self._base.entropy()._value,
                                    self._reinterpreted_batch_rank))
