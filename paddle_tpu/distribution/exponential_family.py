"""ExponentialFamily base: entropy and KL via the log-normalizer.

Reference: python/paddle/distribution/exponential_family.py:50 computes
entropy with the Bregman-divergence trick, differentiating the log normalizer
w.r.t. the natural parameters via the autograd tape. TPU-native design: the
gradient is taken with jax.grad on the pure `_log_normalizer` — no tape,
fully jittable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _wrap

__all__ = ["ExponentialFamily"]


class ExponentialFamily(Distribution):
    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        """H = -<carrier> + A(θ) - Σ θ_i · ∇_i A(θ)  (Bregman identity)."""
        nat = [jnp.asarray(p) for p in self._natural_parameters]
        # broadcast shared scalar parameters to the full batch first, else
        # jax.grad sums their per-batch gradients into one number
        common = jnp.broadcast_shapes(*(p.shape for p in nat)) if nat else ()
        nat = [jnp.broadcast_to(p, common) for p in nat]

        def log_norm_sum(*ps):
            return self._log_normalizer(*ps).sum()

        grads = jax.grad(log_norm_sum, argnums=tuple(range(len(nat))))(*nat)
        ent = -self._mean_carrier_measure + self._log_normalizer(*nat)
        for p, g in zip(nat, grads):
            term = p * g
            # event-axis parameters (e.g. Dirichlet concentration) reduce
            # over the event axis down to the entropy's batch rank
            if term.ndim > ent.ndim:
                term = term.sum(tuple(range(ent.ndim, term.ndim)))
            ent = ent - term
        return _wrap(ent)
