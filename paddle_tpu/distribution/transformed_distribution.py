"""TransformedDistribution: push a base distribution through transforms.

Reference: python/paddle/distribution/transformed_distribution.py.
"""
from __future__ import annotations

from .distribution import Distribution, _sum_rightmost, _value, _wrap
from .transform import ChainTransform, Transform

__all__ = ["TransformedDistribution"]


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        if not all(isinstance(t, Transform) for t in transforms):
            raise TypeError("transforms must be Transform instances")
        self._base = base
        self._transforms = list(transforms)
        chain = ChainTransform(self._transforms)
        base_shape = base.batch_shape + base.event_shape
        out_shape = chain.forward_shape(base_shape)
        event_rank = max(chain.codomain_event_dim, len(base.event_shape))
        super().__init__(
            batch_shape=out_shape[:len(out_shape) - event_rank],
            event_shape=out_shape[len(out_shape) - event_rank:])

    @property
    def transforms(self):
        return self._transforms

    def sample(self, shape=()):
        x = self._base.sample(shape)._value
        for t in self._transforms:
            x = t._forward(x)
        return _wrap(x)

    def rsample(self, shape=()):
        x = self._base.rsample(shape)._value
        for t in self._transforms:
            x = t._forward(x)
        return _wrap(x)

    def log_prob(self, value):
        """Change of variables: log p(y) = log p(x) − Σ log|det J_t(x_t)|."""
        y = _value(value)
        log_det = 0.0
        event_rank = len(self.event_shape)
        for t in reversed(self._transforms):
            x = t._inverse(y)
            ld = t._forward_log_det_jacobian(x)
            log_det = log_det + _sum_rightmost(
                ld, event_rank - t.codomain_event_dim)
            y = x
            event_rank = (event_rank - t.codomain_event_dim
                          + t.domain_event_dim)
        base_lp = self._base.log_prob(_wrap(y))._value
        base_lp = _sum_rightmost(
            base_lp, event_rank - len(self._base.event_shape))
        return _wrap(base_lp - log_det)
