"""Multinomial distribution.

Reference: python/paddle/distribution/multinomial.py
(Multinomial(total_count, probs)). Sampling draws `total_count` categorical
indices with one fused jax.random.categorical call and histograms them with a
one-hot matmul — an MXU-friendly formulation; total_count is static so the
whole path jits.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import gammaln

from .categorical import Categorical
from .distribution import Distribution, _param, _value, _wrap

__all__ = ["Multinomial"]


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        if int(total_count) < 1:
            raise ValueError("total_count must be >= 1")
        self.total_count = int(total_count)
        self.probs = _param(probs)
        self.probs = self.probs / self.probs.sum(-1, keepdims=True)
        super().__init__(batch_shape=self.probs.shape[:-1],
                         event_shape=self.probs.shape[-1:])

    @property
    def mean(self):
        return _wrap(self.total_count * self.probs)

    @property
    def variance(self):
        return _wrap(self.total_count * self.probs * (1 - self.probs))

    def log_prob(self, value):
        v = _value(value).astype(self.probs.dtype)
        logp = jnp.log(jnp.where(self.probs > 0, self.probs, 1.0))
        return _wrap(gammaln(jnp.asarray(self.total_count + 1.0))
                     - gammaln(v + 1).sum(-1) + (v * logp).sum(-1))

    def sample(self, shape=()):
        shape = tuple(shape)
        k = self.probs.shape[-1]
        n = self.total_count
        draws = jax.random.categorical(
            self._key(), jnp.log(self.probs), axis=-1,
            shape=(n,) + shape + self.batch_shape)
        counts = jax.nn.one_hot(draws, k, dtype=self.probs.dtype).sum(0)
        return _wrap(counts)

    def entropy(self):
        """n·H(p) − lgamma(n+1) + Σ_i E_{x~Binom(n,p_i)}[lgamma(x+1)],
        the exact decomposition the reference uses
        (multinomial.py entropy via the binomial pmf over the support)."""
        n = self.total_count
        p = self.probs
        cat_h = Categorical(p).entropy()._value
        support = jnp.arange(1, n + 1, dtype=p.dtype)
        support = support.reshape((-1,) + (1,) * p.ndim)
        log_pmf = (gammaln(jnp.asarray(n + 1.0))
                   - gammaln(support + 1) - gammaln(n - support + 1)
                   + support * jnp.log(jnp.where(p > 0, p, 1.0))
                   + (n - support) * jnp.log1p(-jnp.where(p < 1, p, 0.0)))
        # a zero-probability category contributes pmf 0 for every k >= 1 —
        # the masked log above would otherwise leave log C(n,k) behind
        pmf = jnp.where(p > 0, jnp.exp(log_pmf), 0.0)
        corr = (pmf * gammaln(support + 1)).sum((0, -1))
        return _wrap(n * cat_h - gammaln(jnp.asarray(n + 1.0)) + corr)
