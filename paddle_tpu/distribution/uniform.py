"""Uniform distribution.

Reference: python/paddle/distribution/uniform.py (Uniform(low, high)).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _param, _value, _wrap

__all__ = ["Uniform"]


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _param(low)
        self.high = _param(high)
        b = jnp.broadcast_shapes(self.low.shape, self.high.shape)
        super().__init__(batch_shape=b)

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to((self.low + self.high) / 2,
                                      self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to((self.high - self.low) ** 2 / 12,
                                      self.batch_shape))

    def sample(self, shape=(), seed=0):
        return self.rsample(shape)

    def rsample(self, shape=()):
        out = self._extend_shape(shape)
        u = jax.random.uniform(self._key(), out, self.low.dtype)
        return _wrap(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _value(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return _wrap(jnp.where(inside, lp, -jnp.inf))

    def cdf(self, value):
        v = _value(value)
        return _wrap(jnp.clip((v - self.low) / (self.high - self.low), 0, 1))

    def entropy(self):
        return _wrap(jnp.broadcast_to(jnp.log(self.high - self.low),
                                      self.batch_shape))
