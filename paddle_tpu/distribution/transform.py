"""Bijective (and injective) transforms for TransformedDistribution.

Reference: python/paddle/distribution/transform.py:59 (Transform with
forward/inverse/forward_log_det_jacobian and the 13-transform zoo).
TPU-native design: each transform is a pair of pure jnp maps plus an
analytic log-det; everything composes under jit/vmap/grad.
"""
from __future__ import annotations

import enum
import math

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .distribution import _sum_rightmost, _value, _wrap

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


class Type(enum.Enum):
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"

    @classmethod
    def is_injective(cls, t):
        return t in (cls.BIJECTION, cls.INJECTION)


class Transform:
    _type = Type.BIJECTION
    # number of event dims the transform consumes/produces
    domain_event_dim = 0
    codomain_event_dim = 0

    @classmethod
    def _is_injective(cls):
        return Type.is_injective(cls._type)

    def __call__(self, x):
        from .distribution import Distribution
        from .transformed_distribution import TransformedDistribution

        if isinstance(x, Distribution):
            return TransformedDistribution(x, [self])
        if isinstance(x, Transform):
            return ChainTransform([x, self])  # composition: x applies first
        return self.forward(x)  # Tensor / ndarray / scalar / list

    def forward(self, x):
        return _wrap(self._forward(_value(x)))

    def inverse(self, y):
        return _wrap(self._inverse(_value(y)))

    def forward_log_det_jacobian(self, x):
        return _wrap(self._forward_log_det_jacobian(_value(x)))

    def inverse_log_det_jacobian(self, y):
        v = _value(y)
        return _wrap(-self._forward_log_det_jacobian(self._inverse(v)))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # subclass hooks --------------------------------------------------------
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # principal (non-negative) branch


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _value(loc)
        self.scale = _value(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)
        self.domain_event_dim = max(
            (t.domain_event_dim for t in self.transforms), default=0)
        self.codomain_event_dim = max(
            (t.codomain_event_dim for t in self.transforms), default=0)

    def _is_injective(self):
        return all(t._is_injective() for t in self.transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            ld = t._forward_log_det_jacobian(x)
            # reduce per-transform extra event axes so terms sum at the
            # chain's batch rank
            total = total + _sum_rightmost(
                ld, self.domain_event_dim - t.domain_event_dim)
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class IndependentTransform(Transform):
    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        self.domain_event_dim = (base.domain_event_dim
                                 + self.reinterpreted_batch_rank)
        self.codomain_event_dim = (base.codomain_event_dim
                                   + self.reinterpreted_batch_rank)

    def _is_injective(self):
        return self.base._is_injective()

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        return _sum_rightmost(self.base._forward_log_det_jacobian(x),
                              self.reinterpreted_batch_rank)


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _value(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        if (math.prod(self.in_event_shape)
                != math.prod(self.out_event_shape)):
            raise ValueError("in/out event sizes must match")
        self.domain_event_dim = len(self.in_event_shape)
        self.codomain_event_dim = len(self.out_event_shape)

    def _forward(self, x):
        lead = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(lead + self.out_event_shape)

    def _inverse(self, y):
        lead = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(lead + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        lead = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(lead, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[:len(shape) - n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[:len(shape) - n]) + self.in_event_shape


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class SoftmaxTransform(Transform):
    _type = Type.OTHER
    domain_event_dim = 1
    codomain_event_dim = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)


class StackTransform(Transform):
    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _is_injective(self):
        return all(t._is_injective() for t in self.transforms)

    def _split(self, x):
        return [jnp.squeeze(s, self.axis) for s in
                jnp.split(x, len(self.transforms), axis=self.axis)]

    def _forward(self, x):
        return jnp.stack([t._forward(s) for t, s in
                          zip(self.transforms, self._split(x))], self.axis)

    def _inverse(self, y):
        return jnp.stack([t._inverse(s) for t, s in
                          zip(self.transforms, self._split(y))], self.axis)

    def _forward_log_det_jacobian(self, x):
        return jnp.stack([t._forward_log_det_jacobian(s) for t, s in
                          zip(self.transforms, self._split(x))], self.axis)


class StickBreakingTransform(Transform):
    """R^k -> open (k+1)-simplex via stick breaking."""

    _type = Type.BIJECTION
    domain_event_dim = 1
    codomain_event_dim = 1

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        z1m_cumprod = jnp.cumprod(1 - z, axis=-1)
        pad = jnp.ones(x.shape[:-1] + (1,), x.dtype)
        return (jnp.concatenate([z, pad], -1)
                * jnp.concatenate([pad, z1m_cumprod], -1))

    def _inverse(self, y):
        y_crop = y[..., :-1]
        k = y_crop.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=y.dtype)
        # logit of each stick fraction: z_i = y_i / (1 - Σ_{j<=i-1} y_j),
        # and 1 - z_i leaves exactly 1 - Σ_{j<=i} y_j of the stick
        sf = 1 - jnp.cumsum(y_crop, axis=-1)
        return jnp.log(y_crop) - jnp.log(sf) + jnp.log(offset)

    def _forward_log_det_jacobian(self, x):
        # Jacobian is lower triangular: ∂y_i/∂x_i = y_i (1 − z_i)
        y = self._forward(x)
        k = x.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        return (jnp.log(y[..., :-1]) + jnp.log1p(-z)).sum(-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh^2 x) = 2(log2 - x - softplus(-2x)), numerically stable
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))
