"""incubate operators: fused softmax-mask, segment reduce, graph ops.

Reference: python/paddle/incubate/operators/softmax_mask_fuse.py:23,
incubate/tensor/math.py:23 (segment_*), incubate/operators/
graph_send_recv.py:22. TPU-native: jnp compositions through the autograd
tape; XLA fuses mask+softmax, and segment reductions use jax.ops.segment_*
(sorted scatter-add lowering).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import apply

__all__ = ["softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
           "segment_sum", "segment_mean", "segment_max", "segment_min",
           "graph_send_recv"]


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) over the last axis (one fused XLA computation)."""
    return apply(lambda a, m: jax.nn.softmax(a + m, axis=-1), x, mask)


def softmax_mask_fuse_upper_triangle(x):
    """softmax with the causal (upper-triangular) mask applied, for
    [batch, heads, seq_q, seq_k] attention scores."""

    def _f(a):
        s_q, s_k = a.shape[-2], a.shape[-1]
        causal = jnp.tril(jnp.ones((s_q, s_k), bool))
        neg = jnp.asarray(jnp.finfo(a.dtype).min, a.dtype)
        return jax.nn.softmax(jnp.where(causal, a, neg), axis=-1)

    return apply(_f, x)


def _num_segments(segment_ids, out_size):
    if out_size is not None:
        return int(out_size)
    ids = segment_ids._value if hasattr(segment_ids, "_value") else segment_ids
    return int(jnp.max(ids)) + 1 if ids.size else 0


def segment_sum(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)
    return apply(lambda d, i: jax.ops.segment_sum(d, i, num_segments=n),
                 data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)

    def _f(d, i):
        s = jax.ops.segment_sum(d, i, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones(i.shape, d.dtype), i,
                                num_segments=n)
        return s / jnp.maximum(c, 1)[(...,) + (None,) * (d.ndim - 1)]

    return apply(_f, data, segment_ids)


def segment_max(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)
    return apply(lambda d, i: jax.ops.segment_max(d, i, num_segments=n),
                 data, segment_ids)


def segment_min(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)
    return apply(lambda d, i: jax.ops.segment_min(d, i, num_segments=n),
                 data, segment_ids)


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    """Gather x[src], scatter-reduce onto dst (message passing primitive)."""
    pool_type = pool_type.lower()
    if pool_type not in ("sum", "mean", "max", "min"):
        raise ValueError(f"unsupported pool_type {pool_type}")
    xv = x._value if hasattr(x, "_value") else jnp.asarray(x)
    n = int(out_size) if out_size is not None else xv.shape[0]
    red = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}.get(pool_type)

    def _f(xx, src, dst):
        msgs = jnp.take(xx, src, axis=0)
        if pool_type == "mean":
            s = jax.ops.segment_sum(msgs, dst, num_segments=n)
            c = jax.ops.segment_sum(jnp.ones(dst.shape, xx.dtype), dst,
                                    num_segments=n)
            return s / jnp.maximum(c, 1)[(...,) + (None,) * (xx.ndim - 1)]
        out = red(msgs, dst, num_segments=n)
        if pool_type in ("max", "min"):
            # empty segments come back +-inf; the reference zeros them
            out = jnp.where(jnp.isinf(out), jnp.zeros_like(out), out)
        return out

    return apply(_f, x, src_index, dst_index)
