"""incubate operators: fused softmax-mask, segment reduce, graph ops.

Reference: python/paddle/incubate/operators/softmax_mask_fuse.py:23,
incubate/tensor/math.py:23 (segment_*), incubate/operators/
graph_send_recv.py:22. TPU-native: jnp compositions through the autograd
tape; XLA fuses mask+softmax, and segment reductions use jax.ops.segment_*
(sorted scatter-add lowering).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import apply

__all__ = ["softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
           "segment_sum", "segment_mean", "segment_max", "segment_min",
           "graph_send_recv", "graph_sample_neighbors", "graph_reindex",
           "graph_khop_sampler"]


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) over the last axis (one fused XLA computation)."""
    return apply(lambda a, m: jax.nn.softmax(a + m, axis=-1), x, mask)


def softmax_mask_fuse_upper_triangle(x):
    """softmax with the causal (upper-triangular) mask applied, for
    [batch, heads, seq_q, seq_k] attention scores."""

    def _f(a):
        s_q, s_k = a.shape[-2], a.shape[-1]
        causal = jnp.tril(jnp.ones((s_q, s_k), bool))
        neg = jnp.asarray(jnp.finfo(a.dtype).min, a.dtype)
        return jax.nn.softmax(jnp.where(causal, a, neg), axis=-1)

    return apply(_f, x)


def _num_segments(segment_ids, out_size):
    if out_size is not None:
        return int(out_size)
    ids = segment_ids._value if hasattr(segment_ids, "_value") else segment_ids
    return int(jnp.max(ids)) + 1 if ids.size else 0


def segment_sum(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)
    return apply(lambda d, i: jax.ops.segment_sum(d, i, num_segments=n),
                 data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)

    def _f(d, i):
        s = jax.ops.segment_sum(d, i, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones(i.shape, d.dtype), i,
                                num_segments=n)
        return s / jnp.maximum(c, 1)[(...,) + (None,) * (d.ndim - 1)]

    return apply(_f, data, segment_ids)


def segment_max(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)
    return apply(lambda d, i: jax.ops.segment_max(d, i, num_segments=n),
                 data, segment_ids)


def segment_min(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)
    return apply(lambda d, i: jax.ops.segment_min(d, i, num_segments=n),
                 data, segment_ids)


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    """Gather x[src], scatter-reduce onto dst (message passing primitive)."""
    pool_type = pool_type.lower()
    if pool_type not in ("sum", "mean", "max", "min"):
        raise ValueError(f"unsupported pool_type {pool_type}")
    xv = x._value if hasattr(x, "_value") else jnp.asarray(x)
    n = int(out_size) if out_size is not None else xv.shape[0]
    red = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}.get(pool_type)

    def _f(xx, src, dst):
        msgs = jnp.take(xx, src, axis=0)
        if pool_type == "mean":
            s = jax.ops.segment_sum(msgs, dst, num_segments=n)
            c = jax.ops.segment_sum(jnp.ones(dst.shape, xx.dtype), dst,
                                    num_segments=n)
            return s / jnp.maximum(c, 1)[(...,) + (None,) * (xx.ndim - 1)]
        out = red(msgs, dst, num_segments=n)
        if pool_type in ("max", "min"):
            # empty segments come back +-inf; the reference zeros them
            out = jnp.where(jnp.isinf(out), jnp.zeros_like(out), out)
        return out

    return apply(_f, x, src_index, dst_index)


def _np_vals(*xs):
    import numpy as np

    return [None if x is None else
            np.asarray(x._value if hasattr(x, "_value") else x)
            for x in xs]


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """Sample up to `sample_size` neighbors per input node from a CSC graph
    (reference incubate/operators/graph_sample_neighbors.py:23). Host-side:
    output size is data-dependent, which XLA cannot express — same reason
    the reference runs it on dedicated kernels outside the graph."""
    import numpy as np

    from ..core.tensor import Tensor

    rowv, colv, nodes, eidv = _np_vals(row, colptr, input_nodes, eids)
    # stochastic across calls, reproducible under paddle.seed: derive the
    # host RNG from the functional PRNG stream
    from ..framework import random as _rnd

    seed = int(jax.random.randint(_rnd.next_key(), (), 0, 2 ** 31 - 1))
    rng = np.random.default_rng(seed)
    out_n, out_c, out_e = [], [], []
    for n in nodes.ravel():
        lo, hi = int(colv[n]), int(colv[n + 1])
        neigh = rowv[lo:hi]
        ids = np.arange(lo, hi)
        if 0 <= sample_size < len(neigh):
            pick = rng.choice(len(neigh), size=sample_size, replace=False)
            neigh = neigh[pick]
            ids = ids[pick]
        out_n.append(neigh)
        out_c.append(len(neigh))
        out_e.append(eidv[ids] if eidv is not None else ids)
    neighbors = Tensor(jnp.asarray(np.concatenate(out_n) if out_n
                                   else np.zeros(0, rowv.dtype)))
    count = Tensor(jnp.asarray(np.asarray(out_c, np.int32)))
    if return_eids:
        return neighbors, count, Tensor(
            jnp.asarray(np.concatenate(out_e) if out_e
                        else np.zeros(0, np.int64)))
    return neighbors, count


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Reindex (input nodes + sampled neighbors) to contiguous local ids
    (reference incubate/operators/graph_reindex.py:23)."""
    import numpy as np

    from ..core.tensor import Tensor

    xv, nv, cv = _np_vals(x, neighbors, count)
    order = {}
    for n in xv.ravel():
        order.setdefault(int(n), len(order))
    for n in nv.ravel():
        order.setdefault(int(n), len(order))
    out_nodes = np.fromiter(order.keys(), dtype=xv.dtype, count=len(order))
    reindex_src = np.asarray([order[int(n)] for n in nv.ravel()],
                             dtype=np.int64)
    # duplicate seeds (normal in khop's concatenated frontiers) must map to
    # the SAME local id — repeat the deduped id, not the seed position
    dst = np.repeat(np.asarray([order[int(n)] for n in xv.ravel()],
                               dtype=np.int64), cv.ravel())
    return (Tensor(jnp.asarray(reindex_src)), Tensor(jnp.asarray(dst)),
            Tensor(jnp.asarray(out_nodes)))


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop sampling + subgraph reindex (reference
    incubate/operators/graph_khop_sampler.py:23)."""
    import numpy as np

    from ..core.tensor import Tensor

    frontier = input_nodes
    all_neigh, all_cnt, all_eids, all_src_nodes = [], [], [], []
    for k in sample_sizes:
        res = graph_sample_neighbors(row, colptr, frontier,
                                     sample_size=int(k), return_eids=True)
        neigh, cnt, eids = res
        all_neigh.append(np.asarray(neigh._value))
        all_cnt.append(np.asarray(cnt._value))
        all_eids.append(np.asarray(eids._value))
        all_src_nodes.append(
            np.asarray(frontier._value if hasattr(frontier, "_value")
                       else frontier).ravel())
        frontier = Tensor(neigh._value)
    neighbors = Tensor(jnp.asarray(np.concatenate(all_neigh)))
    counts = Tensor(jnp.asarray(np.concatenate(all_cnt)))
    seeds = Tensor(jnp.asarray(np.concatenate(all_src_nodes)))
    edge_src, edge_dst, sample_index = graph_reindex(seeds, neighbors,
                                                     counts)
    if return_eids:
        return (edge_src, edge_dst, sample_index, None,
                Tensor(jnp.asarray(np.concatenate(all_eids))))
    return edge_src, edge_dst, sample_index, None
