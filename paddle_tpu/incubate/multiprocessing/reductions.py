"""Cross-process Tensor pickling over shared memory (reference:
python/paddle/incubate/multiprocessing/reductions.py — LoDTensor
reductions through the file_system shm strategy).

TPU-native: device buffers are host-reachable numpy views, so the
reduction writes the array once into a POSIX shared-memory block and the
consumer maps it zero-copy, rebuilds a Tensor, and unlinks the block
(single-consumer contract, matching the reference's file_system
strategy where the segment dies with its consumer). Only host-resident
(CPU/unsharded) tensors are shareable — a sharded device array must be
gathered first, which is the honest semantic on a TPU slice.
"""
from __future__ import annotations

import sys
from multiprocessing import shared_memory
from multiprocessing.reduction import ForkingPickler

import numpy as np

__all__ = ["init_reductions"]


def _supported_check():
    if sys.platform != "linux":
        return False  # reference: linux-only, file_system strategy
    return True


def _rebuild_tensor_shm(shm_name, shape, dtype):
    from ...core.tensor import Tensor

    try:
        shm = shared_memory.SharedMemory(name=shm_name)
    except FileNotFoundError:
        raise RuntimeError(
            f"shared-memory tensor segment {shm_name} is gone — each "
            "pickled Tensor payload is SINGLE-CONSUMER (the first "
            "deserialization frees the segment); deserializing the same "
            "bytes twice is not supported") from None
    try:
        view = np.ndarray(shape, dtype=dtype, buffer=shm.buf)
        arr = np.array(view)  # own copy; the block is freed below
    finally:
        shm.close()
        try:
            shm.unlink()  # single-consumer: the segment dies here
        except FileNotFoundError:
            pass
    return Tensor(arr)


def _rebuild_tensor_inline(arr):
    from ...core.tensor import Tensor

    return Tensor(arr)


# below this size the shm round trip costs more than inline pickle bytes
_INLINE_LIMIT = 4096


def reduce_tensor(t):
    """ForkingPickler reduction for Tensor (reference reductions.py:104)."""
    arr = np.asarray(t._value)
    if not _supported_check() or arr.nbytes <= _INLINE_LIMIT:
        return (_rebuild_tensor_inline, (arr,))
    shm = shared_memory.SharedMemory(create=True, size=arr.nbytes)
    try:
        view = np.ndarray(arr.shape, dtype=arr.dtype, buffer=shm.buf)
        view[...] = arr
        name = shm.name
        # hand ownership to the consumer: without this, the producer's
        # resource_tracker unlinks the segment when the producer exits —
        # racing a consumer that hasn't mapped it yet (dataloader workers
        # exit right after queueing their last batch). The cost is a
        # leaked segment if the payload is never deserialized; that is
        # the same lifetime contract as the reference's file_system
        # strategy.
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    finally:
        shm.close()  # producer unmaps; consumer unlinks
    # ship the dtype OBJECT: .str is lossy for extension dtypes ('<V2'
    # for bfloat16 — the primary dtype on this platform)
    return (_rebuild_tensor_shm, (name, arr.shape, arr.dtype))


def init_reductions():
    """Register the Tensor reduction with multiprocessing's pickler
    (reference reductions.py:182). Pickle reducer dispatch is
    exact-type, so every Tensor subclass that crosses process
    boundaries (Parameter — the common large payload) registers too."""
    if not _supported_check():
        return
    from ...core.tensor import Tensor
    from ...nn.layer.layers import Parameter

    ForkingPickler.register(Tensor, reduce_tensor)
    ForkingPickler.register(Parameter, reduce_tensor)
