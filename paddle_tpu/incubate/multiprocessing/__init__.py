"""paddle.incubate.multiprocessing (reference:
python/paddle/incubate/multiprocessing/__init__.py — the stdlib
multiprocessing namespace with Tensor reductions pre-registered, so
Tensors cross Process/Queue boundaries via shared memory)."""
import multiprocessing

from multiprocessing import *  # noqa: F401,F403

from .reductions import init_reductions  # noqa: E402

__all__ = []
__all__ += multiprocessing.__all__  # type: ignore[attr-defined]

init_reductions()
