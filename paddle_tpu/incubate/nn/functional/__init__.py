"""incubate.nn.functional fused transformer ops.

Reference: python/paddle/incubate/nn/functional/fused_transformer.py:31
(fused_feedforward) and :215 (fused_multi_head_attention) — single CUDA
kernels on GPU. TPU-native design: one Python call composing traced ops;
under jit XLA fuses the elementwise/norm chain into the matmuls, and the
attention core dispatches through scaled_dot_product_attention so the
Pallas flash kernel fires when shapes allow. No hand-written megakernel —
that's the compiler's job on TPU.
"""
from __future__ import annotations

from .... import tensor as T
from ....nn import functional as F

__all__ = ["fused_multi_head_attention", "fused_feedforward"]


def _ln(x, scale, bias, eps):
    size = x.shape[-1]
    return F.layer_norm(x, size, weight=scale, bias=bias, epsilon=eps)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", name=None):
    """residual + dropout2(linear2(dropout1(act(linear1(maybe_ln(x))))))."""
    residual = x
    if pre_layer_norm:
        x = _ln(x, ln1_scale, ln1_bias, ln1_epsilon)
    x = F.linear(x, linear1_weight, linear1_bias)
    x = getattr(F, activation)(x)
    x = F.dropout(x, p=dropout1_rate, training=training, mode=mode)
    x = F.linear(x, linear2_weight, linear2_bias)
    x = F.dropout(x, p=dropout2_rate, training=training, mode=mode)
    out = T.add(residual, x)
    if not pre_layer_norm:
        out = _ln(out, ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, name=None):
    """Self-attention block. qkv_weight is [3, heads, head_dim, embed],
    qkv_bias [3, heads, head_dim] (the reference's fused layout)."""
    if cache_kv is not None:
        raise NotImplementedError("cache_kv is not supported yet")
    b, s, e = x.shape
    three, h, d, _ = qkv_weight.shape
    assert three == 3 and h * d == e, "qkv_weight must be [3,h,d,e]"

    residual = x
    src = _ln(x, pre_ln_scale, pre_ln_bias,
              pre_ln_epsilon) if pre_layer_norm else x
    # one big [e, 3e] matmul keeps the MXU busy; split after
    w = T.transpose(T.reshape(qkv_weight, [3 * h * d, e]), [1, 0])
    qkv = T.matmul(src, w)                                   # [b, s, 3e]
    if qkv_bias is not None:
        qkv = T.add(qkv, T.reshape(qkv_bias, [3 * h * d]))
    qkv = T.reshape(qkv, [b, s, 3, h, d])
    q = T.squeeze(T.slice(qkv, [2], [0], [1]), [2])          # [b, s, h, d]
    k = T.squeeze(T.slice(qkv, [2], [1], [2]), [2])
    v = T.squeeze(T.slice(qkv, [2], [2], [3]), [2])
    out = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate,
        training=training)                                   # [b, s, h, d]
    out = T.reshape(out, [b, s, e])
    out = F.linear(out, linear_weight, linear_bias)
    out = F.dropout(out, p=dropout_rate, training=training, mode=mode)
    out = T.add(residual, out)
    if not pre_layer_norm:
        out = _ln(out, ln_scale, ln_bias, ln_epsilon)
    return out
