"""paddle.incubate (reference: python/paddle/incubate/__init__.py)."""
from . import asp  # noqa: F401
from . import checkpoint  # noqa: F401
from . import distributed  # noqa: F401
from . import autograd  # noqa: F401
from . import nn  # noqa: F401

# NOT imported eagerly (matching the reference): importing
# incubate.multiprocessing registers Tensor reductions with the GLOBAL
# multiprocessing pickler — that side effect must stay opt-in via an
# explicit `import paddle.incubate.multiprocessing`.
from .operators import (  # noqa: F401
    graph_khop_sampler, graph_reindex, graph_sample_neighbors,
    graph_send_recv, segment_max, segment_mean, segment_min, segment_sum,
    softmax_mask_fuse, softmax_mask_fuse_upper_triangle,
)
from .optimizer import LookAhead, ModelAverage  # noqa: F401

__all__ = [
    "LookAhead", "ModelAverage", "softmax_mask_fuse_upper_triangle",
    "softmax_mask_fuse", "graph_send_recv", "graph_sample_neighbors",
    "graph_reindex", "graph_khop_sampler", "segment_sum", "segment_mean",
    "segment_max", "segment_min",
]


def softmax_cross_entropy_blockwise(hidden, weight, labels, block=8192):
    """TPU-native fused CE over a tied projection without materializing
    [N, V] logits (see ops/blockwise_ce.py; capability reference:
    phi/kernels/gpu/cross_entropy_kernel.cu:1 fused softmax+CE)."""
    from ..core.autograd import apply
    from ..ops.blockwise_ce import blockwise_softmax_ce

    return apply(lambda h, w, l: blockwise_softmax_ce(h, w, l, block),
                 hidden, weight, labels)
