"""paddle.incubate.checkpoint (reference:
python/paddle/incubate/checkpoint/__init__.py:15 — re-exports the
auto_checkpoint module)."""
from . import auto_checkpoint  # noqa: F401

__all__ = []
