"""Automatic epoch-level checkpoint/resume (reference:
python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:598
train_epoch_range + AutoCheckpointChecker).

The reference wraps the static Executor and pushes exe/program state to
HDFS between epochs, keyed by job env vars, so a preempted job restarted
by the cluster resumes mid-range. TPU-native: the same generator
contract over the framework's own save/load (numpy state_dicts; orbax
handles the sharded case elsewhere), keyed by a local/NFS checkpoint dir
— on a TPU slice the filesystem IS the job-shared store. Attach the
objects to snapshot (layers/optimizers) via `attach`; every yielded
epoch that completes is durably recorded, and a relaunched process skips
straight to the first incomplete epoch with states restored.
"""
from __future__ import annotations

import json
import os

__all__ = ["train_epoch_range", "AutoCheckpointChecker", "attach",
           "detach"]

_attached = {"models": [], "optimizers": []}


def attach(models=None, optimizers=None):
    """Register what train_epoch_range snapshots (reference: the static
    Executor registers itself; dygraph objects must be named explicitly)."""
    if models is not None:
        _attached["models"] = list(models if isinstance(models, (list,
                                                                 tuple))
                                   else [models])
    if optimizers is not None:
        _attached["optimizers"] = list(
            optimizers if isinstance(optimizers, (list, tuple))
            else [optimizers])


def detach():
    _attached["models"] = []
    _attached["optimizers"] = []


class AutoCheckpointChecker:
    """Env view (reference auto_checkpoint.py:71): where checkpoints live
    and whether auto-checkpointing is enabled for this run."""

    def __init__(self):
        self._job_id = os.environ.get("PADDLE_JOB_ID", "job_default")
        self._root = os.environ.get(
            "PADDLE_CHECKPOINT_DIR",
            os.path.join(".", "auto_checkpoint"))
        self._inter = float(os.environ.get(
            "PADDLE_SAVE_CHECKPOINT_INTER", 0))

    @property
    def job_id(self):
        return self._job_id

    @property
    def save_checkpoint_inter(self):
        return self._inter

    def valid(self):
        return bool(self._root)

    def get_job_path(self):
        return os.path.join(self._root, self._job_id)

    def get_range_checkpoint_path(self, name):
        return os.path.join(self.get_job_path(), "range", name)


class _TrainEpochRange:
    def __init__(self, max_epoch_num, name, save_checkpoint_inter=None):
        self._max = int(max_epoch_num)
        self._name = name
        self._checker = AutoCheckpointChecker()
        if save_checkpoint_inter is not None:
            self._checker._inter = save_checkpoint_inter
        self._path = self._checker.get_range_checkpoint_path(name)
        self._meta_path = os.path.join(self._path, "meta.json")
        self.restored_from = None
        self._next_epoch = 0
        self._restore()

    # -- persistence ------------------------------------------------------
    def _restore(self):
        if not os.path.exists(self._meta_path):
            return
        with open(self._meta_path) as f:
            meta = json.load(f)
        from ...framework.io import load

        state_dir = os.path.join(self._path, meta.get("dir", ""))
        saved_models = sorted(
            f for f in os.listdir(state_dir) if f.endswith(".pdparams")) \
            if os.path.isdir(state_dir) else []
        saved_opts = sorted(
            f for f in os.listdir(state_dir) if f.endswith(".pdopt")) \
            if os.path.isdir(state_dir) else []
        if (saved_models and len(saved_models) != len(_attached["models"]))\
                or (saved_opts
                    and len(saved_opts) != len(_attached["optimizers"])):
            # skipping epochs while leaving ANY fresh-init state in place
            # would silently train garbage — refuse on count mismatch,
            # not just on nothing-attached
            raise RuntimeError(
                f"checkpoint at {state_dir} holds "
                f"{len(saved_models)} model / {len(saved_opts)} optimizer "
                f"states but {len(_attached['models'])} model / "
                f"{len(_attached['optimizers'])} optimizer objects are "
                "attached; call incubate.checkpoint.auto_checkpoint."
                "attach(models=, optimizers=) with the same objects as "
                "the run that saved, BEFORE train_epoch_range")
        self._next_epoch = int(meta.get("epoch_done", -1)) + 1
        for i, m in enumerate(_attached["models"]):
            p = os.path.join(state_dir, f"model_{i}.pdparams")
            if os.path.exists(p):
                m.set_state_dict(load(p))
        for i, o in enumerate(_attached["optimizers"]):
            p = os.path.join(state_dir, f"opt_{i}.pdopt")
            if os.path.exists(p):
                o.set_state_dict(load(p))
        self.restored_from = self._path

    def save_checkpoint(self, epoch):
        from ...framework.io import save

        # the whole state SET is versioned per epoch and the meta commit
        # (atomic) comes last: a crash mid-save leaves meta pointing at
        # the previous COMPLETE set — never a torn model/optimizer mix
        # (a per-file replace could pair an epoch-N model with an
        # epoch-N-1 optimizer)
        step = f"epoch_{epoch}"
        step_dir = os.path.join(self._path, step)
        os.makedirs(step_dir, exist_ok=True)
        for i, m in enumerate(_attached["models"]):
            save(m.state_dict(),
                 os.path.join(step_dir, f"model_{i}.pdparams"))
        for i, o in enumerate(_attached["optimizers"]):
            save(o.state_dict(), os.path.join(step_dir, f"opt_{i}.pdopt"))
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"epoch_done": epoch, "max": self._max,
                       "dir": step}, f)
        os.replace(tmp, self._meta_path)
        # prune superseded epoch dirs (best-effort; meta no longer
        # references them)
        import shutil

        for d in os.listdir(self._path):
            if d.startswith("epoch_") and d != step:
                shutil.rmtree(os.path.join(self._path, d),
                              ignore_errors=True)

    # -- iteration --------------------------------------------------------
    def __iter__(self):
        import time

        last_save = time.monotonic()
        for epoch in range(self._next_epoch, self._max):
            yield epoch
            now = time.monotonic()
            # inter=0 (default): checkpoint every epoch; otherwise only
            # when the interval elapsed or on the final epoch
            if (self._checker.save_checkpoint_inter <= 0
                    or now - last_save >= self._checker.save_checkpoint_inter
                    or epoch == self._max - 1):
                self.save_checkpoint(epoch)
                last_save = now


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None,
                      name="range_0"):
    """for epoch in train_epoch_range(N): ... — epochs already completed
    by a previous (killed) run of the same job are skipped, with attached
    model/optimizer states restored (reference auto_checkpoint.py:598)."""
    return _TrainEpochRange(max_epoch_num, name,
                            save_checkpoint_inter=save_checkpoint_inter)
