"""Automatic SParsity (2:4 structured sparsity).

Reference: python/paddle/fluid/contrib/sparsity/asp.py:1 (ASPHelper,
decorate, prune_model, set_excluded_layers) and utils.py:137
(get/check_mask_1d, get/check_mask_2d_greedy, create_mask, check_sparsity,
calculate_density).

TPU-native: the reference relies on Ampere sparse tensor cores for the 2x
math win; TPU MXUs execute the masked weights dense, so here ASP is a
capability/accuracy feature — masks are computed host-side (numpy, exactly
the reference's selection rules), applied as multiplies, and re-applied
after every optimizer step by the decorated optimizer so training preserves
the n:m pattern end to end.
"""
from __future__ import annotations

from enum import Enum

import numpy as np

__all__ = ["MaskAlgo", "CheckMethod", "calculate_density", "get_mask_1d",
           "check_mask_1d", "get_mask_2d_greedy", "check_mask_2d",
           "create_mask", "check_sparsity", "decorate", "prune_model",
           "set_excluded_layers", "reset_excluded_layers", "ASPHelper"]


class MaskAlgo(Enum):
    MASK_1D = "get_mask_1d"
    MASK_2D_GREEDY = "get_mask_2d_greedy"
    MASK_2D_BEST = "get_mask_2d_greedy"  # greedy is the TPU-side default


class CheckMethod(Enum):
    CHECK_1D = "check_mask_1d"
    CHECK_2D = "check_mask_2d"

    @staticmethod
    def get_checking_method(mask_algo):
        if mask_algo == MaskAlgo.MASK_1D:
            return CheckMethod.CHECK_1D
        return CheckMethod.CHECK_2D


def calculate_density(x):
    x = np.asarray(x)
    return float(np.count_nonzero(x)) / max(x.size, 1)


def _pad_cols(mat, m):
    cols = mat.shape[1]
    pad = (m - cols % m) % m
    if pad:
        mat = np.concatenate([mat, np.zeros((mat.shape[0], pad),
                                            mat.dtype)], axis=1)
    return mat, cols


def get_mask_1d(mat, n, m):
    """Keep the n largest-|.| of every m consecutive row elements
    (reference utils.py:181)."""
    mat = np.asarray(mat)
    padded, cols = _pad_cols(mat, m)
    g = padded.reshape(-1, m)
    order = np.argsort(np.abs(g), axis=1)[:, ::-1][:, :n]
    mask = np.zeros_like(g)
    np.put_along_axis(mask, order, 1.0, axis=1)
    return mask.reshape(padded.shape)[:, :cols].astype(mat.dtype)


def check_mask_1d(mat, n, m):
    mat = np.asarray(mat)
    padded, _ = _pad_cols(mat, m)
    g = padded.reshape(-1, m)
    return bool(np.all(np.count_nonzero(g, axis=1) <= n))


def get_mask_2d_greedy(mat, n, m):
    """n:m on m x m blocks (reference utils.py:314, same algorithm): scan
    each block's entries in descending |value| order, keeping an entry
    while its row AND column still have fewer than n kept. Like the
    reference, this guarantees AT MOST n kept per row/column (>= n zeros,
    the 2-D n:m pattern); the greedy order usually but not always fills
    every row to exactly n."""
    mat = np.asarray(mat)
    rows, cols = mat.shape
    rpad = (m - rows % m) % m
    cpad = (m - cols % m) % m
    padded = np.pad(mat, ((0, rpad), (0, cpad)))
    mask = np.zeros_like(padded)
    for r0 in range(0, padded.shape[0], m):
        for c0 in range(0, padded.shape[1], m):
            block = np.abs(padded[r0:r0 + m, c0:c0 + m])
            sub = np.zeros_like(block)
            row_counts = np.zeros(m, np.int64)
            col_counts = np.zeros(m, np.int64)
            for flat in np.argsort(block, axis=None)[::-1]:
                i, j = divmod(int(flat), m)
                if row_counts[i] == n or col_counts[j] == n:
                    continue
                sub[i, j] = 1.0
                row_counts[i] += 1
                col_counts[j] += 1
            mask[r0:r0 + m, c0:c0 + m] = sub
    return mask[:rows, :cols].astype(mat.dtype)


def check_mask_2d(mat, n, m):
    mat = np.asarray(mat)
    ok_rows = check_mask_1d(mat, n, m)
    ok_cols = check_mask_1d(mat.T, n, m)
    return ok_rows and ok_cols


def create_mask(tensor, func_name=MaskAlgo.MASK_1D, n=2, m=4):
    t = np.asarray(tensor)
    shape = t.shape
    if t.ndim == 1:
        mat = t.reshape(1, -1)
    elif t.ndim == 2:
        mat = t
    elif t.ndim == 4:  # conv [out, in, kh, kw] -> [out, in*kh*kw]
        mat = t.reshape(shape[0], -1)
    else:
        mat = t.reshape(shape[0], -1)
    fn = {MaskAlgo.MASK_1D: get_mask_1d,
          MaskAlgo.MASK_2D_GREEDY: get_mask_2d_greedy,
          MaskAlgo.MASK_2D_BEST: get_mask_2d_greedy}[MaskAlgo(func_name)]
    return fn(mat, n, m).reshape(shape)


def check_sparsity(tensor, func_name=CheckMethod.CHECK_1D, n=2, m=4):
    t = np.asarray(tensor)
    mat = t.reshape(t.shape[0], -1) if t.ndim > 2 else np.atleast_2d(t)
    fn = {CheckMethod.CHECK_1D: check_mask_1d,
          CheckMethod.CHECK_2D: check_mask_2d}[CheckMethod(func_name)]
    return fn(mat, n, m)


class ASPHelper:
    """Reference asp.py:260 ASPHelper — mask registry + supported-layer
    test. Params are matched by structured name."""

    MASK_APPENDDED_NAME = "asp_mask"
    _excluded = set()
    # id(param) -> (weakref(param), mask): weakrefs so pruned models can be
    # garbage-collected; dead entries are swept on each decorated step
    _masks = {}

    @classmethod
    def _is_supported_layer(cls, param_name, param):
        if any(ex in param_name for ex in cls._excluded):
            return False
        v = param._value if hasattr(param, "_value") else param
        if getattr(v, "ndim", 0) < 2:
            return False
        # embeddings / norms excluded by the reference's supported list;
        # here: weights of linear (2-D) and conv (4-D)
        return v.ndim in (2, 4)

    @classmethod
    def prune_model(cls, model, n=2, m=4, mask_algo=MaskAlgo.MASK_1D,
                    with_mask=True):
        import jax.numpy as jnp

        if isinstance(mask_algo, str):
            mask_algo = {"mask_1d": MaskAlgo.MASK_1D,
                         "mask_2d_greedy": MaskAlgo.MASK_2D_GREEDY,
                         "mask_2d_best": MaskAlgo.MASK_2D_BEST}[mask_algo]
        masks = {}
        for name, p in model.named_parameters():
            if not cls._is_supported_layer(name, p):
                continue
            mask = create_mask(np.asarray(p._value), mask_algo, n, m)
            p._value = p._value * jnp.asarray(mask, p._value.dtype)
            if with_mask:
                import weakref

                cls._masks[id(p)] = (weakref.ref(p), jnp.asarray(mask))
                masks[name] = mask
        return masks

    @classmethod
    def _live_masks(cls, restrict_ids=None):
        """(param, mask) pairs still alive; sweeps dead weakrefs. When
        restrict_ids is given, only those params are re-masked (a decorated
        optimizer touches its own parameter list, not other models')."""
        out, dead = [], []
        for pid, (ref, mask) in cls._masks.items():
            p = ref()
            if p is None:
                dead.append(pid)
            elif restrict_ids is None or pid in restrict_ids:
                out.append((p, mask))
        for pid in dead:
            del cls._masks[pid]
        return out

    @classmethod
    def decorate(cls, optimizer):
        return OptimizerWithSparsityGuarantee(optimizer)

    @classmethod
    def reset(cls):
        cls._excluded = set()
        cls._masks = {}


class OptimizerWithSparsityGuarantee:
    """Re-applies the registered masks after every step (reference
    asp.py:605 — the fleet/static path appends mask ops to the program;
    the jitted update here multiplies post-step, which XLA fuses into the
    update program on the blessed paths)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def _own_param_ids(self):
        params = getattr(self._optimizer, "_parameter_list", None)
        return None if params is None else {id(p) for p in params}

    def _apply_masks(self):
        for p, mask in ASPHelper._live_masks(self._own_param_ids()):
            p._value = p._value * mask.astype(p._value.dtype)

    def step(self):
        out = self._optimizer.step()
        self._apply_masks()
        return out

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        out = self._optimizer.minimize(loss, startup_program, parameters,
                                       no_grad_set)
        self._apply_masks()
        return out


def set_excluded_layers(main_program=None, param_names=None):
    """Exclude params whose structured name contains any given string
    (reference asp.py:38; main_program kept for signature parity)."""
    if param_names is None and main_program is not None and \
            not hasattr(main_program, "global_block"):
        param_names = main_program  # called as set_excluded_layers(names)
    ASPHelper._excluded |= set(param_names or [])


def reset_excluded_layers(main_program=None):
    ASPHelper._excluded = set()


def decorate(optimizer):
    return ASPHelper.decorate(optimizer)


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    return ASPHelper.prune_model(model, n, m, mask_algo, with_mask)
