"""incubate.autograd: functional differentiation (vjp, jvp, Jacobian,
Hessian).

Reference: python/paddle/incubate/autograd/__init__.py over
autograd/functional.py:22 (vjp), :79 (jvp), :698 (jacobian), :1133
(hessian). TPU-native: direct composition of jax.vjp/jvp/jacrev/hessian —
each call is one traced XLA program, no tape walking.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "jacobian", "hessian"]


def _unwrap(x):
    if isinstance(x, (list, tuple)):
        return [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                for t in x]
    return [x._value if isinstance(x, Tensor) else jnp.asarray(x)]


def _wrap_like(vals, template):
    out = [Tensor(v) for v in vals]
    if isinstance(template, (list, tuple)):
        return out
    return out[0]


def _pure(func):
    def f(*vals):
        out = func(*[Tensor(v) for v in vals])
        if isinstance(out, (list, tuple)):
            return tuple(o._value for o in out)
        return out._value

    return f


def vjp(func, xs, v=None):
    """Returns (func(xs), vjp(v)) — cotangents w.r.t. xs."""
    vals = _unwrap(xs)
    f = _pure(func)
    out, pullback = jax.vjp(f, *vals)
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        cv = _unwrap(v)
        cot = tuple(cv) if isinstance(out, tuple) else cv[0]
    grads = pullback(cot)
    outs = ([Tensor(o) for o in out] if isinstance(out, tuple)
            else Tensor(out))
    return outs, _wrap_like(list(grads), xs)


def jvp(func, xs, v=None):
    """Returns (func(xs), jvp along v) — forward-mode tangents."""
    vals = _unwrap(xs)
    f = _pure(func)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in vals)
    else:
        tangents = tuple(_unwrap(v))
    out, tangent_out = jax.jvp(f, tuple(vals), tangents)
    outs = ([Tensor(o) for o in out] if isinstance(out, tuple)
            else Tensor(out))
    touts = ([Tensor(t) for t in tangent_out]
             if isinstance(tangent_out, tuple) else Tensor(tangent_out))
    return outs, touts


class Jacobian:
    """Lazy Jacobian d func / d xs, indexable like the reference
    (J[:], J[i, j]); computed once with jax.jacrev (reverse mode rides the
    same vjp machinery the tape uses)."""

    def __init__(self, func, xs, is_batched=False):
        if is_batched:
            raise NotImplementedError(
                "batched Jacobians are not supported; vmap the unbatched "
                "Jacobian instead")
        import math

        vals = _unwrap(xs)
        f = _pure(func)
        # f re-enters user code under jax traces below: suspend the
        # per-op dispatch cache for the whole derivation (tracelint
        # suspend-audit)
        from ..core import dispatch as _dispatch

        with _dispatch.suspend():  # fuselint: ok[FL004] Jacobian traces fn whole; a deferred op inside would leak tracers
            out_struct = jax.eval_shape(f, *vals)
            if isinstance(out_struct, tuple):
                raise NotImplementedError(
                    "multi-output Jacobian is not supported; stack/concat "
                    "the outputs into one tensor")
            out_size = math.prod(out_struct.shape)
            jacs = jax.jacrev(f, argnums=tuple(range(len(vals))))(*vals)
        # argnums as a tuple always yields a tuple of blocks; flatten each
        # to [out_size, in_size] and stack inputs on the column axis — the
        # reference's 2-D Jacobian view
        self._jac = jnp.concatenate(
            [j.reshape(out_size, -1) for j in jacs], axis=-1)

    @property
    def shape(self):
        return list(self._jac.shape)

    def __getitem__(self, idx):
        return Tensor(self._jac[idx])

    def numpy(self):
        import numpy as np

        return np.asarray(self._jac)


class Hessian:
    """Hessian of a scalar-output func w.r.t. xs (reference Hessian)."""

    def __init__(self, func, xs, is_batched=False):
        if is_batched:
            raise NotImplementedError(
                "batched Hessians are not supported; vmap the unbatched "
                "Hessian instead")
        vals = _unwrap(xs)
        f = _pure(func)

        def scalar(*a):
            out = f(*a)
            return out.reshape(()) if hasattr(out, "reshape") else out

        if len(vals) == 1:
            h = jax.hessian(scalar)(vals[0])
            n = vals[0].size
            h = h.reshape(n, n)
        else:
            h = jax.hessian(scalar, argnums=tuple(range(len(vals))))(*vals)
            rows = []
            for i in range(len(vals)):
                row = [h[i][j].reshape(vals[i].size, vals[j].size)
                       for j in range(len(vals))]
                rows.append(jnp.concatenate(row, axis=1))
            h = jnp.concatenate(rows, axis=0)
        self._h = h

    @property
    def shape(self):
        return list(self._h.shape)

    def __getitem__(self, idx):
        return Tensor(self._h[idx])

    def numpy(self):
        import numpy as np

        return np.asarray(self._h)


def jacobian(func, inputs, create_graph=False, allow_unused=False):
    """paddle.autograd.functional.jacobian-style eager helper."""
    return Jacobian(func, inputs)[:]


def hessian(func, inputs, create_graph=False, allow_unused=False):
    return Hessian(func, inputs)[:]
