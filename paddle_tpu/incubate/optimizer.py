"""incubate optimizers: LookAhead, ModelAverage.

Reference: python/paddle/incubate/optimizer/lookahead.py (slow/fast weights,
slow += alpha*(fast-slow) every k steps) and modelaverage.py (windowed
parameter averaging with apply()/restore()).
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if inner_optimizer is None:
            raise ValueError("inner optimizer cannot be None")
        assert 0.0 <= alpha <= 1.0 and k >= 1
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        super().__init__(
            learning_rate=alpha,
            parameters=inner_optimizer._parameter_list, name=name)
        self._slow = {}   # param id -> slow weight array
        self._k_step = 0

    def step(self):
        self.inner_optimizer.step()
        self._k_step += 1
        if self._k_step % self.k:
            return
        for p in self._param_list:
            if p.stop_gradient:
                continue
            slow = self._slow.get(id(p))
            if slow is None:
                # first sync: slow weights start at the pre-LookAhead value
                slow = p._value
            slow = slow + self.alpha * (p._value - slow)
            self._slow[id(p)] = slow
            p._value = slow

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._param_list]

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_k_step"] = self._k_step
        return sd

    def set_state_dict(self, sd):
        self._k_step = int(sd.pop("lookahead_k_step", 0))
        self.inner_optimizer.set_state_dict(sd)


class ModelAverage(Optimizer):
    """Trailing-window parameter average, matching the reference's
    average_accumulates recurrence (fluid/operators/average_accumulates_op.h):
    sum_1 accumulates each step; every 16384 updates it folds into sum_2
    (precision); when num_accumulates reaches the dynamic window
    min(max_average_window, num_updates * rate) (and >= min_average_window),
    sum_3 <- sum_1 + sum_2 and the recent sums restart. The average is
    (sum_1 + sum_2 + sum_3) / (num_accumulates + old_num_accumulates).
    """

    _MAX_FOLD = 16384

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=parameters, name=name)
        self.avg_window_rate = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._sum_1 = {}
        self._sum_2 = {}
        self._sum_3 = {}
        self._num_updates = 0
        self._num_accumulates = 0
        self._old_num_accumulates = 0
        self._backup = None

    def step(self):
        """Accumulate the current parameter values into the window."""
        self._num_updates += 1
        self._num_accumulates += 1
        for p in self._param_list:
            if p.stop_gradient:
                continue
            acc = self._sum_1.get(id(p))
            self._sum_1[id(p)] = p._value if acc is None else acc + p._value
        if self._num_updates % self._MAX_FOLD == 0:
            for k, v in self._sum_1.items():
                self._sum_2[k] = v + self._sum_2.get(k, 0)
            self._sum_1 = {}
        window = min(self.max_average_window,
                     self._num_updates * self.avg_window_rate)
        if (self._num_accumulates >= self.min_average_window
                and self._num_accumulates >= window):
            self._sum_3 = {
                k: self._sum_1.get(k, 0) + self._sum_2.get(k, 0)
                for k in set(self._sum_1) | set(self._sum_2)}
            self._sum_1 = {}
            self._sum_2 = {}
            self._old_num_accumulates = self._num_accumulates
            self._num_accumulates = 0
        self._global_step += 1

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()
        return None, []

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        """Swap in the averaged parameters (context manager)."""
        total = self._num_accumulates + self._old_num_accumulates
        if total == 0:
            yield
            return
        self._backup = {}
        for p in self._param_list:
            if p.stop_gradient:
                continue
            s = (self._sum_1.get(id(p), 0) + self._sum_2.get(id(p), 0)
                 + self._sum_3.get(id(p), 0))
            self._backup[id(p)] = p._value
            p._value = (s / total).astype(p._value.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._param_list:
            if id(p) in self._backup:
                p._value = self._backup[id(p)]
        self._backup = None
