"""paddle.incubate.distributed.models.moe (reference:
python/paddle/incubate/distributed/models/moe) — MoELayer + gates.
Aliases the mesh-native implementation in paddle.distributed.moe
(GShard top-k dispatch via all_to_all on the ep axis) and the routing
helper ops."""
from paddle_tpu.distributed.models.moe import (  # noqa: F401
    _assign_pos, _limit_by_capacity, _number_count,
    _prune_gate_by_capacity, _random_routing,
)
from paddle_tpu.distributed.moe import MoELayer  # noqa: F401

__all__ = ["MoELayer"]
