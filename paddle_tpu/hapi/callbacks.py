"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import numbers
import os
import time

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "LRScheduler", "EarlyStopping", "VisualDL", "ReduceLROnPlateau",
           "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def append(self, cbk):
        self.callbacks.append(cbk)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def on_begin(self, mode, logs=None):
        self._call(f"on_{mode}_begin", logs)

    def on_end(self, mode, logs=None):
        self._call(f"on_{mode}_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_begin", step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_end", step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self.epochs = None
        self.steps = None
        self._t0 = self._step_t0 = time.time()

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._step_t0 = time.time()
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose == 0 or (step + 1) % self.log_freq:
            return
        self._print("step", step, logs)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            self._print("epoch end, step", logs.get("step", 0), logs)

    def on_eval_end(self, logs=None):
        if self.verbose and logs:
            self._print("eval done, step", logs.get("step", 0), logs)

    def _print(self, prefix, step, logs):
        items = []
        for k, v in (logs or {}).items():
            if k in ("step", "batch_size"):
                continue
            if isinstance(v, numbers.Number):
                items.append(f"{k}: {v:.4f}")
            elif isinstance(v, (list, tuple)) and v and \
                    isinstance(v[0], numbers.Number):
                items.append(f"{k}: " + "/".join(f"{x:.4f}" for x in v))
        total = f"/{self.steps}" if self.steps else ""
        dt = (time.time() - self._step_t0) / max(step + 1, 1)
        print(f"{prefix} {step + 1}{total} - " + " - ".join(items) +
              f" - {dt * 1000:.0f}ms/step")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") and not isinstance(lr, float) \
            else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda a, b: a > b + self.min_delta
            self.best = -np.inf
        else:
            self.better = lambda a, b: a < b - self.min_delta
            self.best = np.inf
        if baseline is not None:
            # reference semantics: improvement must beat the baseline
            self.best = baseline
        self.wait = 0

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get("eval_" + self.monitor, logs.get(self.monitor))
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.better(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.params.get("save_dir"):
                self.model.save(os.path.join(self.params["save_dir"],
                                             "best_model"))
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Epoch {epoch}: early stopping (best "
                          f"{self.monitor}={self.best:.5f})")


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        from ..optimizer.lr import ReduceOnPlateau as _Sched

        self._mk = lambda lr: _Sched(lr, mode="min" if mode != "max" else
                                     "max", factor=factor, patience=patience,
                                     threshold=min_delta, cooldown=cooldown,
                                     min_lr=min_lr, verbose=verbose)
        self._sched = None

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get("eval_" + self.monitor, logs.get(self.monitor))
        if cur is None:
            return
        opt = self.model._optimizer
        if self._sched is None:
            self._sched = self._mk(opt.get_lr())
        self._sched.step(cur)
        if not hasattr(opt._learning_rate, "step"):
            opt.set_lr(self._sched())


class VisualDL(Callback):
    """Scalar logger (reference integrates visualdl; here: jsonl fallback
    consumable by tensorboard importers)."""

    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._f = None
        self._step = 0

    def on_train_begin(self, logs=None):
        os.makedirs(self.log_dir, exist_ok=True)
        import json  # noqa: F401

        self._f = open(os.path.join(self.log_dir, "scalars.jsonl"), "a")

    def on_train_batch_end(self, step, logs=None):
        import json

        self._step += 1
        rec = {k: float(v) for k, v in (logs or {}).items()
               if isinstance(v, numbers.Number)}
        rec["global_step"] = self._step
        self._f.write(json.dumps(rec) + "\n")

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks) if callbacks else []
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        pass  # epoch-wise scheduler stepping handled by Model.fit
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"batch_size": batch_size, "epochs": epochs, "steps": steps,
                   "verbose": verbose, "metrics": metrics or [],
                   "save_dir": save_dir})
    return cl
