"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import numbers
import os
import time

import numpy as np

from ..runtime import diagnostics as _diagnostics
from ..runtime import telemetry as _telemetry
from ..runtime import tracing as _tracing

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "LRScheduler", "EarlyStopping", "VisualDL", "ReduceLROnPlateau",
           "ResilienceCallback", "TelemetryCallback", "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def append(self, cbk):
        self.callbacks.append(cbk)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def on_begin(self, mode, logs=None):
        self._call(f"on_{mode}_begin", logs)

    def on_end(self, mode, logs=None):
        self._call(f"on_{mode}_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_begin", step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_end", step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self.epochs = None
        self.steps = None
        self._t0 = self._step_t0 = time.time()

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._step_t0 = time.time()
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose == 0 or (step + 1) % self.log_freq:
            return
        self._print("step", step, logs)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            self._print("epoch end, step", logs.get("step", 0), logs)

    def on_eval_end(self, logs=None):
        if self.verbose and logs:
            self._print("eval done, step", logs.get("step", 0), logs)

    def _print(self, prefix, step, logs):
        items = []
        for k, v in (logs or {}).items():
            if k in ("step", "batch_size"):
                continue
            if isinstance(v, numbers.Number):
                items.append(f"{k}: {v:.4f}")
            elif isinstance(v, (list, tuple)) and v and \
                    isinstance(v[0], numbers.Number):
                items.append(f"{k}: " + "/".join(f"{x:.4f}" for x in v))
        total = f"/{self.steps}" if self.steps else ""
        dt = (time.time() - self._step_t0) / max(step + 1, 1)
        print(f"{prefix} {step + 1}{total} - " + " - ".join(items) +
              f" - {dt * 1000:.0f}ms/step")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") and not isinstance(lr, float) \
            else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda a, b: a > b + self.min_delta
            self.best = -np.inf
        else:
            self.better = lambda a, b: a < b - self.min_delta
            self.best = np.inf
        if baseline is not None:
            # reference semantics: improvement must beat the baseline
            self.best = baseline
        self.wait = 0

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get("eval_" + self.monitor, logs.get(self.monitor))
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.better(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.params.get("save_dir"):
                self.model.save(os.path.join(self.params["save_dir"],
                                             "best_model"))
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Epoch {epoch}: early stopping (best "
                          f"{self.monitor}={self.best:.5f})")


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        from ..optimizer.lr import ReduceOnPlateau as _Sched

        self._mk = lambda lr: _Sched(lr, mode="min" if mode != "max" else
                                     "max", factor=factor, patience=patience,
                                     threshold=min_delta, cooldown=cooldown,
                                     min_lr=min_lr, verbose=verbose)
        self._sched = None

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get("eval_" + self.monitor, logs.get(self.monitor))
        if cur is None:
            return
        opt = self.model._optimizer
        if self._sched is None:
            self._sched = self._mk(opt.get_lr())
        self._sched.step(cur)
        if not hasattr(opt._learning_rate, "step"):
            opt.set_lr(self._sched())


class VisualDL(Callback):
    """Scalar logger (reference integrates visualdl; here: jsonl
    consumable by tensorboard importers) — a thin wrapper over the
    telemetry scalars sink (`runtime.telemetry.ScalarsSink`), which
    flushes PER BATCH: the old implementation buffered until
    `on_train_end`, so a ``kill -9`` mid-run (the exact scenario the
    resilience runtime hardens) lost the entire log."""

    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._sink = None
        self._step = 0

    def on_train_begin(self, logs=None):
        self._sink = _telemetry.ScalarsSink(self.log_dir)

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        rec = {k: float(v) for k, v in (logs or {}).items()
               if isinstance(v, numbers.Number)}
        self._sink.write(self._step, rec)

    def on_train_end(self, logs=None):
        if self._sink:
            self._sink.close()


class TelemetryCallback(Callback):
    """Continuous per-step telemetry from `Model.fit`: the producer that
    gives the metrics registry and event stream their time axis.

        model.fit(data, epochs=2, callbacks=[
            TelemetryCallback("telemetry_log", export_every=50)])

    Per train batch it records step wall time, throughput
    (samples/sec), loss, the fused step's global grad norm (when a
    guard enabled ``engine.want_grad_norm``) and device-memory gauges —
    into the registry (``paddle_tpu_step_seconds`` histogram,
    ``paddle_tpu_train_steps_total``, ...), the structured event stream
    (one ``train_step`` event per batch), and a per-step scalars file
    (`ScalarsSink`, TensorBoard-consumable). Every `export_every` steps
    — and at train end — it mirrors the runtime's authoritative
    snapshots into the registry (`telemetry.sync_runtime_metrics`) and
    rewrites the Prometheus textfile, so a scraper watching
    ``metrics.prom`` follows the run live and the exported counters
    reconcile exactly with ``dispatch_stats()`` / ``fault_events()``.

    With the ``PADDLE_TPU_TELEMETRY=0`` kill switch the callback is
    inert (no files, no registry traffic).
    """

    def __init__(self, log_dir=None, export_every=50, step_events=True,
                 scalars=True, snapshot_jsonl=False):
        super().__init__()
        self.log_dir = log_dir
        self.export_every = max(1, int(export_every))
        self.step_events = step_events
        self.scalars = scalars
        self.snapshot_jsonl = snapshot_jsonl
        self.global_step = 0
        self._sink = None
        self._active = False
        self._t_last = None

    # registry families are looked up per use (never cached across a
    # registry reset); the lookup is a dict get under an uncontended lock
    def _metrics(self):
        return (
            _telemetry.counter("paddle_tpu_train_steps_total",
                               "train batches completed"),
            _telemetry.histogram("paddle_tpu_step_seconds",
                                 "train step wall time"),
            _telemetry.gauge("paddle_tpu_loss", "last train loss"),
            _telemetry.gauge("paddle_tpu_throughput_samples_per_sec",
                             "samples/sec over the last step"),
            _telemetry.gauge("paddle_tpu_grad_norm",
                             "last global L2 grad norm (when enabled)"),
        )

    def on_train_begin(self, logs=None):
        self._active = _telemetry.enabled()
        if not self._active:
            return
        d = self.log_dir
        try:
            d = _telemetry.configure(self.log_dir)
            if d is None:
                d = _telemetry.configure(self.log_dir or "telemetry_log")
            if self.scalars:
                self._sink = _telemetry.ScalarsSink(d)
        except OSError as e:
            # telemetry must never kill the training it observes: an
            # unwritable log dir degrades to registry-only collection
            self._sink = None
            import warnings

            warnings.warn(f"paddle_tpu telemetry: cannot write to "
                          f"{d!r} ({e}) — event stream and "
                          "file exports disabled for this run", stacklevel=2)
        self._t_last = time.perf_counter()
        _telemetry.emit("train_begin", epochs=self.params.get("epochs"),
                        steps=self.params.get("steps"),
                        batch_size=self.params.get("batch_size"))

    def on_train_batch_end(self, step, logs=None):
        if not self._active:
            return
        now = time.perf_counter()
        dt = now - (self._t_last if self._t_last is not None else now)
        self._t_last = now
        self.global_step += 1
        logs = logs or {}
        loss = logs.get("loss")
        if isinstance(loss, (list, tuple)):
            loss = loss[0] if loss else None
        batch_size = logs.get("batch_size") or \
            self.params.get("batch_size") or 0
        throughput = (batch_size / dt) if dt > 0 and batch_size else None
        gnorm = getattr(getattr(self.model, "_engine", None),
                        "last_grad_norm", None)
        if gnorm is not None:
            try:
                gnorm = float(np.asarray(gnorm))
            except Exception:  # noqa: BLE001 — unreadable device value
                gnorm = None
        steps_c, step_h, loss_g, thr_g, gn_g = self._metrics()
        steps_c.inc()
        step_h.observe(dt)
        # whole-step span from the SAME dt the histogram observed: the
        # timeline's step lane reconciles exactly with
        # paddle_tpu_step_seconds (tracing.reconcile_with_metrics)
        _tracing.emit_span("train_step", "step", time.time() - dt, dt,
                           step=self.global_step)
        if loss is not None:
            loss_g.set(float(loss))
        if throughput is not None:
            thr_g.set(throughput)
        if gnorm is not None:
            gn_g.set(gnorm)
        mem = _telemetry.poll_memory_gauges()
        rec = {"step": self.global_step, "step_s": round(dt, 6)}
        if loss is not None:
            rec["loss"] = float(loss)
        if throughput is not None:
            rec["throughput"] = round(throughput, 3)
        if gnorm is not None:
            rec["grad_norm"] = gnorm
        if mem and mem.get("bytes_in_use"):
            rec["memory_bytes_in_use"] = int(mem["bytes_in_use"])
        if self.step_events:
            _telemetry.emit("train_step", **rec)
        if self._sink is not None:
            self._sink.write(self.global_step,
                             {k: v for k, v in rec.items() if k != "step"})
        if self.global_step % self.export_every == 0:
            self._export()

    def _export(self):
        try:
            _telemetry.sync_runtime_metrics()
            _telemetry.write_prometheus()
            if _telemetry.pushgateway_addr():
                # opt-in direct push (multihost ranks without a local
                # textfile collector); push_prometheus itself degrades
                # a dead gateway to a warning + push_failures event
                _telemetry.push_prometheus()
            if _telemetry.otlp_endpoint():
                # opt-in OTLP/HTTP export to an OpenTelemetry
                # collector; same degrade-to-warning contract
                _telemetry.push_otlp()
            # keep the span timeline as durable as the metrics at every
            # export boundary (the unflushed tail is all a crash loses)
            _tracing.flush()
            if self.snapshot_jsonl:
                _telemetry.append_snapshot_jsonl(
                    extra={"step": self.global_step})
        except Exception as e:  # noqa: BLE001 — a full disk mid-run must
            # degrade (the run outranks its observability), not abort fit
            import warnings

            warnings.warn(f"paddle_tpu telemetry: export failed "
                          f"({type(e).__name__}: {e}) — continuing",
                          stacklevel=2)

    def on_train_end(self, logs=None):
        if not self._active:
            return
        self._export()
        _telemetry.emit("train_end", steps=self.global_step)
        if self._sink is not None:
            self._sink.close()
            self._sink = None


class ResilienceCallback(Callback):
    """Fault-tolerant `Model.fit`: checkpoint-interval saves, bad-step
    rollback, and heartbeats — the whole resilience story from the
    high-level API.

        model.fit(data, epochs=3, callbacks=[
            ResilienceCallback("ckpts", save_interval=50,
                               watchdog_timeout=300)])

    Composes the hardened runtime pieces (io/checkpoint.py,
    distributed/elastic.py, runtime/resilience.py):

    * every `save_interval` global steps, the full train state (params,
      buffers, optimizer slots, step) is checkpointed asynchronously
      with integrity manifests; an initial checkpoint at train begin
      guarantees a rollback target before the first interval;
    * a non-finite loss — or, with `grad_norm_threshold`, an
      exploding-but-finite per-step global grad norm (exposed by the
      fused train step as `engine.last_grad_norm`) — rolls
      params/optimizer back to the newest complete checkpoint and
      training skips forward; after `max_consecutive_rollbacks` bad
      steps in a row the escalation callback runs (default: stop
      training via `model.stop_training`);
    * a heartbeat file advances per step; with `watchdog_timeout` a
      background watchdog reports a hung loop — including one that
      hangs before the first heartbeat — via `on_stall` (default: stop
      training);
    * with `resume=True` a restarted fit continues from the newest
      complete checkpoint (kill-and-resume, the elastic contract);
    * in **cluster mode** — automatic when ``PADDLE_TPU_CLUSTER_DIR``
      is set or jax reports more than one process, or explicit via
      `cluster=` (a `coordination.ClusterContext` or a shared store
      directory) — the whole story goes multihost: heartbeats publish
      into the shared store and the watchdog (always started in
      cluster mode — it hosts the quorum scan) escalates only on a
      QUORUM of stale ranks (one slow peer = `peer_stale` fault event,
      a silent one = declared down cluster-wide), resume restores the
      newest step EVERY rank verified complete (host-0 rendezvous
      agreement, so a rank killed mid-async-save can never make peers
      diverge), and at every checkpoint boundary each rank publishes
      its telemetry snapshot while host 0 merges them into ONE
      rank-labeled Prometheus textfile + cluster-wide fault log.

    Every degradation path is observable in
    `profiler.fault_events()` / `dispatch_stats()["fault_events"]`.
    """

    def __init__(self, ckpt_dir, save_interval=100, max_to_keep=3,
                 async_save=True, watchdog_timeout=None, step_deadline=None,
                 run_deadline=None, watchdog_poll=5.0,
                 max_consecutive_rollbacks=3, on_escalate=None, on_stall=None,
                 verify_integrity=True, resume=True,
                 grad_norm_threshold=None, cluster=None,
                 peer_stale_after=None, peer_dead_after=None,
                 cluster_quorum=0.5, rendezvous_timeout=30.0):
        super().__init__()
        self.grad_norm_threshold = grad_norm_threshold
        self.ckpt_dir = ckpt_dir
        self.save_interval = max(1, int(save_interval))
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        self.watchdog_timeout = watchdog_timeout
        self.step_deadline = step_deadline
        self.run_deadline = run_deadline
        self.watchdog_poll = watchdog_poll
        self.max_consecutive_rollbacks = max_consecutive_rollbacks
        self.on_escalate = on_escalate
        self.on_stall = on_stall
        self.verify_integrity = verify_integrity
        self.resume = resume
        self.cluster = cluster
        self.peer_stale_after = peer_stale_after
        self.peer_dead_after = peer_dead_after
        self.cluster_quorum = cluster_quorum
        self.rendezvous_timeout = rendezvous_timeout
        self.global_step = 0
        self._mngr = None
        self._em = None
        self._guard = None
        self._cluster = None
        self._merge_thread = None

    # -- state capture / write-back -----------------------------------------
    def _state(self):
        net = self.model.network
        engine = self.model._engine
        state = {
            "params": {k: p._value for k, p in net.named_parameters()},
            "bufs": {k: b._value for k, b in net.named_buffers()
                     if b is not None and hasattr(b, "_value")},
            "step": np.asarray(self.global_step, np.int64),
        }
        if engine._opt_states is not None:
            # orbax trees round-trip dict keys as str
            state["opt"] = {str(k): dict(v)
                            for k, v in engine._opt_states.items()}
        # orbax rejects empty tree nodes (a network with no buffers)
        return {k: v for k, v in state.items()
                if not (isinstance(v, dict) and not v)}

    def _write_back(self, state):
        import jax.numpy as jnp

        net = self.model.network
        engine = self.model._engine
        params = dict(net.named_parameters())
        for k, v in (state.get("params") or {}).items():
            if k in params:
                params[k]._value = jnp.asarray(v)
        bufs = dict(net.named_buffers())
        for k, v in (state.get("bufs") or {}).items():
            if k in bufs and hasattr(bufs[k], "_value"):
                bufs[k]._value = jnp.asarray(v)
        opt = state.get("opt")
        if opt:
            engine._opt_states = {
                int(k): {kk: jnp.asarray(vv) for kk, vv in v.items()}
                for k, v in opt.items()}
        step = state.get("step")
        return None if step is None else int(np.asarray(step))

    def _save_step(self, step):
        self._mngr.save(step, self._state())
        self._cluster_checkpoint_boundary()

    def _restore(self, step=None):
        """Restore params/opt from the newest complete checkpoint at or
        below `step`; returns the step restored, or None when nothing
        restorable exists (the checkpoint manager already recorded the
        fault events for any fallback it performed)."""
        try:
            state = self._mngr.restore(step)
        except FileNotFoundError:
            return None
        restored = self._write_back(state)
        return self._mngr.last_restored_step if restored is None else restored

    # -- cluster mode --------------------------------------------------------
    def _cluster_setup(self):
        from ..distributed import coordination

        c = self.cluster
        if c is None:
            # automatic: PADDLE_TPU_CLUSTER_DIR, or >1 jax process (the
            # checkpoint root is the shared filesystem multihost jobs
            # already have, so the store defaults under it)
            self._cluster = coordination.cluster_context(
                default_dir=os.path.join(self.ckpt_dir, ".cluster"))
        elif isinstance(c, coordination.ClusterContext):
            self._cluster = c
        else:  # a store / shared directory: identity from env/jax
            self._cluster = coordination.ClusterContext(
                c, coordination.cluster_rank(),
                coordination.cluster_world_size())
        if self._cluster is not None:
            coordination.init_cluster_telemetry(self._cluster)
        return self._cluster

    # wall-clock slack between hosts when judging publication/agreement
    # freshness: pod hosts are NTP-disciplined well under this
    CLUSTER_CLOCK_SKEW_S = 5.0

    def _cluster_resume_step(self):
        """The step EVERY rank verified complete, agreed through the
        host-0 rendezvous (None = fresh start). A rank killed
        mid-async-save never published its torn step, so the agreement
        excludes it by construction.

        Freshness matters on both legs: the leader only counts
        publications at least as new as this restart toward its
        expected-ranks wait (a dead rank's stale list still joins the
        final intersection — that is the conservative input the
        protocol wants), and a follower only accepts an agreement doc
        at least as new as its OWN publication (a back-to-back rerun
        must never read the previous run's agreement). Every failure
        degrades — timeout falls back to this rank's own view of the
        published lists — rather than raising into `fit()`."""
        from ..distributed.coordination import rendezvous
        from ..io.checkpoint import latest_common_complete_step

        ctx = self._cluster
        published_at = time.time()
        self._mngr.publish_complete(ctx.store, ctx.rank)
        # the agreement key must not alias a PREVIOUS run's doc:
        # schedulers that restart all ranks with one job incarnation id
        # export PADDLE_TPU_CLUSTER_RUN_ID and the key is namespaced by
        # it (exact, clock-free)
        run_id = os.environ.get("PADDLE_TPU_CLUSTER_RUN_ID")
        if run_id:
            import re

            run_id = re.sub(r"[^A-Za-z0-9._-]", "_", run_id)[:64]
        rdv_name = (f"restore_step_{run_id}" if run_id else "restore_step")
        # followers reject agreement docs older than their own
        # publication minus (one leader wait + skew): tight enough to
        # exclude a run that ended before this restart wave, loose
        # enough that a follower scheduled up to rendezvous_timeout
        # after the leader still accepts its early publication. Kept
        # even under a run id: a SINGLE rank relaunched inside one
        # incarnation must not read the incarnation-start agreement
        # (there is no leader republishing for it) — it should fall
        # back to the live publications instead
        min_wall = (published_at - self.rendezvous_timeout
                    - self.CLUSTER_CLOCK_SKEW_S)
        if ctx.is_leader:
            common = latest_common_complete_step(
                ctx.store, expected_ranks=ctx.world_size,
                timeout=self.rendezvous_timeout,
                min_wall=published_at - self.CLUSTER_CLOCK_SKEW_S)
            rendezvous(ctx.store, rdv_name, {"step": common},
                       leader=True)
            return common, True
        payload = rendezvous(
            ctx.store, rdv_name,
            # the leader may spend a full rendezvous_timeout waiting
            # for publications (a dead rank never republishes) BEFORE
            # it publishes the agreement — a follower deadline equal to
            # the leader's races it on sub-second skew and degrades to
            # the local fallback on every such restart
            timeout=2.0 * self.rendezvous_timeout
            + self.CLUSTER_CLOCK_SKEW_S,
            min_wall=min_wall)
        if payload is None:
            # rendezvous_timeouts already recorded: degrade to this
            # rank's own intersection of whatever publications exist.
            # NOT a confirmed agreement — the caller must not truncate
            # history on it (it may be older than the true agreement)
            return latest_common_complete_step(
                ctx.store, expected_ranks=None, timeout=0.0,
                world_size=ctx.world_size), False
        return payload.get("step"), True

    def _cluster_checkpoint_boundary(self, wait=False):
        """Per-rank publications + host-0 merge at a checkpoint
        boundary: complete-step list (coordinated restore), telemetry
        registry snapshot, and — on the leader — the cluster-wide
        merged Prometheus textfile + fault log. The merge re-reads
        every rank's publication and event stream, so on the leader it
        runs in a background thread (skipped while the previous merge
        is still running) rather than blocking the step loop; `wait`
        joins it (train end). Failures degrade to a warning;
        observability must never kill the run."""
        ctx = self._cluster
        if ctx is None:
            return
        try:
            # flush this rank's span buffer BEFORE the leader merges:
            # the cluster timeline covers every rank up to its latest
            # checkpoint boundary, not its latest buffer overflow
            _tracing.flush()
            self._mngr.publish_complete(ctx.store, ctx.rank)
            _telemetry.sync_runtime_metrics()
            _telemetry.publish_registry(ctx.store, ctx.rank)
            if ctx.is_leader:
                push = _telemetry.pushgateway_addr() is not None
                if wait:
                    # train end: drain any in-flight merge, then merge
                    # synchronously so the final artifacts include the
                    # final publications. If the in-flight merge is
                    # STILL running after the timed join, skip the
                    # synchronous one: tmp paths are thread-keyed now
                    # (no corruption), but two racing merges would
                    # still publish in arbitrary order and the older
                    # result could land last — the in-flight merge
                    # lands near-final data on its own
                    drained = True
                    if self._merge_thread is not None:
                        self._merge_thread.join(timeout=30)
                        drained = not self._merge_thread.is_alive()
                        if drained:
                            self._merge_thread = None
                    if drained:
                        _telemetry.merge_cluster(ctx.store, push=push)
                    else:
                        import warnings

                        warnings.warn(
                            "paddle_tpu ResilienceCallback: background "
                            "cluster merge still running at train end — "
                            "final merge skipped (the in-flight one "
                            "will land)", stacklevel=2)
                elif self._merge_thread is None or \
                        not self._merge_thread.is_alive():
                    import threading

                    def _merge():
                        try:
                            _telemetry.merge_cluster(ctx.store, push=push)
                        except Exception:  # noqa: BLE001 — observability
                            pass

                    self._merge_thread = threading.Thread(
                        target=_merge, daemon=True)
                    self._merge_thread.start()
        except Exception as e:  # noqa: BLE001 — degrade, never raise
            import warnings

            warnings.warn(
                f"paddle_tpu ResilienceCallback: cluster publication "
                f"failed ({type(e).__name__}: {e}) — continuing",
                stacklevel=2)

    # -- lifecycle -----------------------------------------------------------
    def on_train_begin(self, logs=None):
        from ..distributed.elastic import ElasticManager
        from ..io.checkpoint import CheckpointManager
        from ..runtime.resilience import BadStepGuard

        # ask the fused step for its per-step grad norm (opt-in: the
        # extra all-gradients reduction is only paid under a guard);
        # train_batch rebuilds the step fn if it was traced without it
        engine = getattr(self.model, "_engine", None)
        if engine is not None:
            engine.want_grad_norm = True

        # arm the crash-and-hang layer for this run: bundles (and the
        # flight-recorder's on-disk spill) default under the checkpoint
        # dir unless PADDLE_TPU_DIAGNOSTICS_DIR already points
        # elsewhere; fatal-signal/excepthook handlers + the opt-in
        # statusz server ride along. Never raises into fit().
        _diagnostics.ensure_installed(
            default_dir=os.path.join(self.ckpt_dir, "diagnostics"))
        self._mngr = CheckpointManager(
            self.ckpt_dir, max_to_keep=self.max_to_keep,
            async_save=self.async_save,
            verify_integrity=self.verify_integrity)
        self._cluster_setup()
        cluster_kwargs = {}
        if self._cluster is not None:
            cluster_kwargs = dict(
                cluster=self._cluster,
                # a usable default even when no local watchdog_timeout
                # was configured (the ElasticManager fallback of 3600s
                # would make peer staleness invisible for an hour)
                peer_stale_after=(
                    self.peer_stale_after
                    if self.peer_stale_after is not None
                    else self.watchdog_timeout or 300.0),
                peer_dead_after=self.peer_dead_after,
                cluster_quorum=self.cluster_quorum)
        self._em = ElasticManager(
            self.ckpt_dir, timeout=self.watchdog_timeout or 3600.0,
            save_interval=self.save_interval, save_fn=self._save_step,
            step_deadline=self.step_deadline, run_deadline=self.run_deadline,
            **cluster_kwargs)
        self.global_step = 0
        if self.resume:
            if self._cluster is not None:
                coordinated = True
                agreed = False
                try:
                    step, agreed = self._cluster_resume_step()
                except Exception as e:  # noqa: BLE001 — store I/O: degrade
                    from ..runtime.resilience import record_fault

                    record_fault(
                        "rendezvous_timeouts",
                        f"coordinated restore degraded to local: "
                        f"{type(e).__name__}: {e}")
                    step = None
                    coordinated = False
                if coordinated:
                    restored = (self._restore(step)
                                if step is not None else None)
                else:
                    # split/unwritable store: rank-local resilience
                    # stays fully active — restore this rank's own
                    # newest complete checkpoint, exactly what the
                    # recorded fault message promises
                    restored = self._restore()
                if coordinated and agreed and step is not None and \
                        restored != step:
                    # this rank's copy of the agreed step failed to
                    # restore (corruption fallback landed below it):
                    # peers run from `step` while this rank holds
                    # `restored` — divergence that must be LOUD, and
                    # this rank's copy of the agreed step must survive
                    # for a retry, so no truncation either
                    from ..runtime.resilience import record_fault

                    record_fault(
                        "restore_fallbacks",
                        f"cluster divergence: restored {restored} != "
                        f"agreed step {step}")
                    import warnings

                    warnings.warn(
                        f"paddle_tpu ResilienceCallback: restored step "
                        f"{restored} instead of the cluster-agreed "
                        f"{step} (local copy failed verification) — "
                        "this rank has DIVERGED from its peers",
                        stacklevel=2)
                elif coordinated and agreed and restored is not None:
                    # coordinated-restart truncation: the cluster agreed
                    # to resume from `restored` — this rank's steps past
                    # it are an abandoned future (they would collide
                    # with upcoming interval saves and mislead per-rank
                    # rollback). GATED ON A RENDEZVOUS-CONFIRMED
                    # agreement: a timeout-fallback step is this rank's
                    # local guess and may be OLDER than the true
                    # agreement — truncating on it could destroy the
                    # very step the leader picked. A fresh-start
                    # agreement (None) likewise deletes NOTHING.
                    try:
                        self._mngr.discard_after(restored)
                        self._mngr.publish_complete(self._cluster.store,
                                                    self._cluster.rank)
                    except Exception:  # noqa: BLE001 — best-effort
                        pass
            else:
                restored = self._restore()
            if restored is not None:
                self.global_step = restored + 1

        def _rollback(bad_step):
            # ROADMAP item 3 gap: a NaN loss is deterministic across
            # SPMD ranks, so every rank's guard trips on the same step —
            # but each rank restoring its OWN newest complete checkpoint
            # can land on different steps (one rank's newest save failed
            # verification and fell back further), silently forking the
            # cluster. Route the rollback target through the same host-0
            # agreement as coordinated restore; only when the agreement
            # itself is unreachable does a rank degrade to its local
            # newest — loudly, via the recorded fault.
            step = None
            if self._cluster is not None:
                from ..distributed.elastic import agreed_rollback_step
                from ..runtime.resilience import record_fault

                try:
                    step = agreed_rollback_step(
                        self._cluster, self.ckpt_dir, bad_step,
                        rendezvous_timeout=self.rendezvous_timeout,
                        clock_skew=self.CLUSTER_CLOCK_SKEW_S)
                except Exception as e:  # noqa: BLE001 — store errors
                    record_fault("restore_fallbacks",
                                 "rollback agreement failed: "
                                 f"{type(e).__name__}: {e}")
                    step = None
            restored = (self._restore(step) if step is not None
                        else self._restore())
            if self._cluster is not None and step is not None and \
                    restored != step:
                from ..runtime.resilience import record_fault

                record_fault(
                    "restore_fallbacks",
                    f"rollback divergence: restored {restored} != "
                    f"agreed step {step}")
            if restored is None:
                import warnings

                warnings.warn(
                    f"paddle_tpu ResilienceCallback: bad step {bad_step} "
                    "with no restorable checkpoint"
                    + (" common to every rank" if self._cluster is not None
                       else "")
                    + " — parameters NOT rolled back", stacklevel=2)

        def _escalate(step, n):
            # N consecutive bad steps is a terminal diagnosis moment:
            # freeze the evidence before the default stop
            _diagnostics.maybe_dump(
                "rollback_escalation",
                extra={"step": step, "consecutive_rollbacks": n})
            if self.on_escalate is not None:
                self.on_escalate(step, n)
            else:
                self.model.stop_training = True

        self._guard = BadStepGuard(
            _rollback, max_consecutive=self.max_consecutive_rollbacks,
            on_escalate=_escalate,
            grad_norm_threshold=self.grad_norm_threshold)

        def _stall(info):
            if self.on_stall is not None:
                self.on_stall(info)
            else:
                self.model.stop_training = True

        # cluster mode starts the watchdog UNCONDITIONALLY: the watchdog
        # loop is where the quorum scan runs, and peers publishing
        # heartbeats nobody reads would make protocol 1 silently inert
        # in the documented default configuration (no watchdog_timeout)
        if self.watchdog_timeout is not None or self._cluster is not None:
            self._em.start_watchdog(on_stall=_stall,
                                    poll=self.watchdog_poll)
        # an immediate checkpoint guarantees a rollback target exists
        # before the first save interval (a NaN on step 0 must have
        # somewhere finite to roll back TO). Skipped when this exact
        # step is already complete on disk: orbax's force=True does not
        # overwrite an existing step (StepAlreadyExistsError), and the
        # rollback target already exists — reachable on a cluster
        # fresh-start whose dir still holds a previous run's step 0
        from ..io.checkpoint import complete_steps

        if self.global_step not in complete_steps(self.ckpt_dir):
            self._mngr.save(self.global_step, self._state(), force=True)
        elif self.global_step == 0:
            # a complete step 0 that this run did NOT just restore is a
            # previous run's leftovers: it stays the rollback target
            # (same as before — rollback restores newest-complete), but
            # that must be loud, not silent
            import warnings

            warnings.warn(
                "paddle_tpu ResilienceCallback: initial checkpoint "
                "skipped — step 0 on disk predates this run, and a "
                "rollback would restore ITS weights, not this run's "
                "fresh initialization", stacklevel=2)

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        loss = logs.get("loss")
        if isinstance(loss, (list, tuple)):
            loss = loss[0] if loss else None
        # per-step global grad norm from the fused train step: lets the
        # guard catch exploding-but-finite steps (threshold rollback),
        # not just non-finite losses
        gnorm = getattr(getattr(self.model, "_engine", None),
                        "last_grad_norm", None)
        good = True
        if loss is not None or gnorm is not None:
            good = self._guard.check(self.global_step, loss, grad_norm=gnorm)
        if good:
            self._em.tick(self.global_step)
        self.global_step += 1

    def on_train_end(self, logs=None):
        if self._em is not None:
            self._em.stop()
        if self._mngr is not None:
            # final checkpoint so a follow-up fit resumes at the end
            self._mngr.save(self.global_step, self._state(), force=True)
            self._mngr.wait()
            self._cluster_checkpoint_boundary(wait=True)
            self._mngr.close()


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks) if callbacks else []
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        pass  # epoch-wise scheduler stepping handled by Model.fit
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"batch_size": batch_size, "epochs": epochs, "steps": steps,
                   "verbose": verbose, "metrics": metrics or [],
                   "save_dir": save_dir})
    return cl
