"""paddle.hapi (reference: python/paddle/hapi)."""
from . import callbacks  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger,
    ReduceLROnPlateau, ResilienceCallback, TelemetryCallback, VisualDL,
)
from .model import Model  # noqa: F401
from .summary import summary  # noqa: F401
from .flops import flops  # noqa: F401
