"""paddle.Model high-level API (reference: python/paddle/hapi/model.py).

TPU-native core: `_JitStepEngine` compiles the ENTIRE train step — forward,
loss, backward, optimizer update, buffer (BN stat) updates — into one XLA
program with donated buffers. Eager Python touches the device once per step
to feed the batch; everything else stays in HBM. This is the path that gives
TPU parity/win over the reference's op-by-op dygraph step (SURVEY §3).
"""
from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd as _ag
from ..core.tensor import Tensor
from ..framework import random as rnd
from ..framework.io import load as _load
from ..framework.io import save as _save
from ..io import DataLoader, Dataset
from ..metric import Metric
from ..runtime import telemetry as _telemetry
from ..runtime import tracing as _tracing
from .callbacks import CallbackList, config_callbacks

__all__ = ["Model"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _as_tensors(batch):
    if isinstance(batch, (list, tuple)):
        return [b if isinstance(b, Tensor) else Tensor(np.asarray(b))
                for b in batch]
    return [batch if isinstance(batch, Tensor) else Tensor(np.asarray(batch))]


def _grad_norm(grads):
    """Global L2 norm over a grad pytree, computed inside the fused step
    (f32 accumulation) so BadStepGuard can flag exploding-but-finite
    steps without a second backward."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.float32(0.0)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in leaves))


def _note_first_step(kind):
    from ..runtime import warmup as _warmup

    _warmup.note_first_step(kind)


class _JitStepEngine:
    """Compiles train/eval/predict steps over the network's param pytree."""

    def __init__(self, model):
        self.model = model
        self._train_fn = None
        self._grad_fn = None
        self._apply_fn = None
        self._eval_fn = None
        self._opt_states = None
        self._accum_grads = None
        # per-step global L2 grad norm from the fused step (device array;
        # BadStepGuard reads it host-side to catch exploding-but-finite
        # steps). Opt-in via want_grad_norm (ResilienceCallback sets it):
        # the norm is a full extra reduction over every gradient leaf,
        # which users without a guard must not pay. None until the first
        # train step with the flag on.
        self.last_grad_norm = None
        self.want_grad_norm = False
        self._computes_norm = False  # what the BUILT step fns bake
        self._recorded = set()  # program names already shape-recorded

    # -- pure functions ----------------------------------------------------
    def _forward_loss(self, param_vals, buf_vals, xs, ys, key, training):
        net = self.model.network
        loss_fn = self.model._loss
        amp_level = self.model._amp_level
        # the mode is a SCOPED override, not per-layer mutation: flipping
        # live `training` flags inside a traced pure function invites a
        # re-entrant-trace heisenbug (round-3 verdict weak #7)
        from ..nn.layer.layers import training_mode

        # suspend the per-op dispatch cache: this body is traced into one
        # fused program, so nested per-op jit entries would only add
        # trace-time overhead and throwaway cache keys. dispatch.suspend
        # also flushes + suspends eager trace fusion (core/fusion.py) —
        # deferring ops inside an outer whole-step trace would record
        # tracers, and the outer program fuses everything anyway
        from ..core import dispatch as _dispatch

        with training_mode(training, net.sublayers(include_self=True)), \
                rnd.key_scope(key), _ag.no_grad(), _dispatch.suspend():  # fuselint: ok[FL004] the whole-step jit trace owns fusion's job here (one program already)
            ctx = None
            if amp_level:
                from .. import amp as amp_mod

                ctx = amp_mod.auto_cast(level=amp_level)
                ctx.__enter__()
            try:
                xs_t = [Tensor(x) for x in xs]
                out, new_bufs = net.functional_call(
                    {k: Tensor(v) for k, v in {**param_vals,
                                               **buf_vals}.items()},
                    *xs_t)
            finally:
                if ctx is not None:
                    ctx.__exit__(None, None, None)
            outs = out if isinstance(out, (list, tuple)) else [out]
            loss = None
            if loss_fn is not None and ys is not None:
                ys_t = [Tensor(y) for y in ys]
                loss = loss_fn(*outs, *ys_t)
                if isinstance(loss, (list, tuple)):
                    from .. import tensor as T

                    loss = T.add_n([l for l in loss])
        loss_raw = loss._value.astype(jnp.float32) if loss is not None else None
        outs_raw = [o._value for o in outs]
        return loss_raw, outs_raw, new_bufs

    def _build_train(self):
        opt = self.model._optimizer
        engine = self
        compute_norm = self._computes_norm = self.want_grad_norm

        meta = opt.param_meta({k: p for k, p in
                               self.model.network.named_parameters()
                               if not p.stop_gradient})
        clip = getattr(opt, "_grad_clip", None)

        def step(param_vals, opt_states, buf_vals, xs, ys, lr, key):
            def loss_of(pv):
                loss, outs, new_bufs = engine._forward_loss(
                    pv, buf_vals, xs, ys, key, training=True)
                return loss, (outs, new_bufs)
            (loss, (outs, new_bufs)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(param_vals)
            gnorm = _grad_norm(grads) if compute_norm else jnp.float32(0.0)
            new_params, new_states = opt.functional_update(
                param_vals, grads, opt_states, lr, meta=meta, clip=clip)
            return new_params, new_states, new_bufs, loss, outs, gnorm

        # donate params + opt states (large, rewritten in place by XLA);
        # buf_vals must NOT be donated: it also carries non-trainable params
        # whose arrays live on after the step
        return jax.jit(step, donate_argnums=(0, 1))

    def _build_grad(self):
        engine = self
        compute_norm = self._computes_norm = self.want_grad_norm

        def step(param_vals, buf_vals, xs, ys, key):
            def loss_of(pv):
                loss, outs, new_bufs = engine._forward_loss(
                    pv, buf_vals, xs, ys, key, training=True)
                return loss, (outs, new_bufs)
            (loss, (outs, new_bufs)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(param_vals)
            gnorm = _grad_norm(grads) if compute_norm else jnp.float32(0.0)
            return grads, loss, outs, new_bufs, gnorm

        return jax.jit(step)

    def _build_apply(self):
        opt = self.model._optimizer
        meta = opt.param_meta({k: p for k, p in
                               self.model.network.named_parameters()
                               if not p.stop_gradient})
        clip = getattr(opt, "_grad_clip", None)

        def apply_step(param_vals, opt_states, grads, lr):
            return opt.functional_update(param_vals, grads, opt_states, lr,
                                         meta=meta, clip=clip)

        return jax.jit(apply_step, donate_argnums=(0, 1))

    def _build_eval(self):
        engine = self

        def step(param_vals, buf_vals, xs, ys, key):
            loss, outs, _ = engine._forward_loss(param_vals, buf_vals, xs, ys,
                                                 key, training=False)
            return loss, outs

        return jax.jit(step)

    # -- mutable state sync ------------------------------------------------
    def _param_dict(self):
        return {k: p._value for k, p in self.model.network.named_parameters()
                if not p.stop_gradient}

    def _buf_dict(self):
        d = {k: p._value for k, p in self.model.network.named_parameters()
             if p.stop_gradient}
        for k, b in self.model.network.named_buffers():
            if isinstance(b, Tensor):
                d[k] = b._value
        return d

    def _write_back(self, new_params, new_bufs):
        net = self.model.network
        params = dict(net.named_parameters())
        for k, v in new_params.items():
            params[k]._value = v
        bufs = {k: b for k, b in net.named_buffers() if isinstance(b, Tensor)}
        for k, v in new_bufs.items():
            tgt = bufs.get(k)
            if tgt is None:
                tgt = params.get(k)
            if tgt is not None:
                tgt._value = v

    def train_batch(self, xs, ys, update=True):
        params = self._param_dict()
        if self._opt_states is None:
            self._opt_states = self.model._optimizer.functional_init_states(
                params)
        bufs = self._buf_dict()
        lr = jnp.asarray(self.model._optimizer.get_lr(), jnp.float32)
        key = rnd.next_key()
        if update and self._accum_grads is None:
            # fast path: one fused XLA program (rebuilt if the grad-norm
            # request changed since it was traced — the flag is baked in)
            if self._train_fn is None or \
                    self._computes_norm != self.want_grad_norm:
                self._train_fn = self._build_train()
            self._record_signature("hapi.train_step",
                                   (params, self._opt_states, bufs, xs, ys,
                                    lr, key))
            new_params, self._opt_states, new_bufs, loss, outs, gnorm = \
                self._train_fn(params, self._opt_states, bufs, xs, ys, lr,
                               key)
            self.last_grad_norm = gnorm if self._computes_norm else None
            self._write_back(new_params, new_bufs)
            _note_first_step("hapi_step")
            return loss, outs
        # accumulation path: grads computed now, applied on the update call
        if self._grad_fn is None or \
                self._computes_norm != self.want_grad_norm:
            self._grad_fn = self._build_grad()
        grads, loss, outs, new_bufs, gnorm = self._grad_fn(params, bufs, xs,
                                                           ys, key)
        self.last_grad_norm = gnorm if self._computes_norm else None
        if self._accum_grads is None:
            self._accum_grads = grads
        else:
            self._accum_grads = jax.tree_util.tree_map(
                jnp.add, self._accum_grads, grads)
        if update:
            if self._apply_fn is None:
                self._apply_fn = self._build_apply()
            new_params, self._opt_states = self._apply_fn(
                params, self._opt_states, self._accum_grads, lr)
            self._accum_grads = None
            self._write_back(new_params, new_bufs)
        else:
            self._write_back({}, new_bufs)
        return loss, outs

    def eval_batch(self, xs, ys):
        if self._eval_fn is None:
            self._eval_fn = self._build_eval()
        params = self._param_dict()
        bufs = self._buf_dict()
        key = rnd.next_key()
        self._record_signature("hapi.eval_step", (params, bufs, xs, ys, key))
        loss, outs = self._eval_fn(params, bufs, xs, ys, key)
        return loss, outs

    def _record_signature(self, name, args):
        """Record the whole-step input signature for the warm-start
        shape manifest, once per program name (BEFORE the call: donated
        buffers are dead afterwards)."""
        if name in self._recorded:
            return
        self._recorded.add(name)
        from ..runtime import warmup as _warmup

        _warmup.record_program(name, args)


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._amp_level = None
        self._engine = _JitStepEngine(self)
        self.stop_training = False

    # ---- setup -----------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        ms = _to_list(metrics)
        for m in ms:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} must be paddle.metric.Metric")
        self._metrics = ms
        if amp_configs is not None:
            if isinstance(amp_configs, str):
                self._amp_level = amp_configs
            elif isinstance(amp_configs, dict):
                self._amp_level = amp_configs.get("level", "O1")
        return self

    def warm_start(self, manifest=None):
        """AOT-precompile the fused train/eval steps from a warm-start
        shape manifest (runtime/warmup.py), so the first `fit` batch
        pays neither trace nor XLA compile time — with the persistent
        compile cache enabled every compile here is a disk load.

        `manifest` is a path or manifest dict (None reuses signatures
        already loaded via ``warmup.precompile``). Signatures recorded
        for a different model/batch shape degrade to a
        ``stale_manifests`` fault event, never an error. Returns
        {"train": n, "eval": n} — how many signatures compiled."""
        from ..runtime import warmup as _warmup

        if manifest is not None:
            _warmup.precompile(manifest)
        stats = {"train": 0, "eval": 0}
        if self._optimizer is not None and self._loss is not None and \
                _warmup.pending_programs().get("hapi.train_step"):
            if self._engine._train_fn is None:
                self._engine._train_fn = self._engine._build_train()
            stats["train"] = _warmup.prewarm_program(
                "hapi.train_step", self._engine._train_fn)
        if _warmup.pending_programs().get("hapi.eval_step"):
            if self._engine._eval_fn is None:
                self._engine._eval_fn = self._engine._build_eval()
            stats["eval"] = _warmup.prewarm_program(
                "hapi.eval_step", self._engine._eval_fn)
        return stats

    # ---- single-batch APIs ----------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        xs = [t._value if isinstance(t, Tensor) else jnp.asarray(np.asarray(t))
              for t in _as_tensors(inputs)]
        ys = None
        if labels is not None:
            ys = [t._value if isinstance(t, Tensor)
                  else jnp.asarray(np.asarray(t)) for t in _as_tensors(labels)]
        loss, outs = self._engine.train_batch(xs, ys, update=update)
        metrics = self._update_metrics(outs, labels)
        return self._loss_out(loss, metrics)

    def eval_batch(self, inputs, labels=None):
        xs = [t._value if isinstance(t, Tensor) else jnp.asarray(np.asarray(t))
              for t in _as_tensors(inputs)]
        ys = None
        if labels is not None:
            ys = [t._value if isinstance(t, Tensor)
                  else jnp.asarray(np.asarray(t)) for t in _as_tensors(labels)]
        loss, outs = self._engine.eval_batch(xs, ys)
        metrics = self._update_metrics(outs, labels)
        return self._loss_out(loss, metrics)

    def predict_batch(self, inputs):
        xs = [t._value if isinstance(t, Tensor) else jnp.asarray(np.asarray(t))
              for t in _as_tensors(inputs)]
        _, outs = self._engine.eval_batch(xs, None)
        return [Tensor(o) for o in outs]

    def _update_metrics(self, outs, labels):
        res = []
        if not self._metrics or labels is None:
            return res
        outs_t = [Tensor(o) for o in outs]
        labels_t = _as_tensors(labels)
        for m in self._metrics:
            c = m.compute(*outs_t, *labels_t)
            r = m.update(*(c if isinstance(c, (list, tuple)) else [c]))
            res.append(r)
        return res

    def _loss_out(self, loss, metrics):
        losses = [float(loss)] if loss is not None else []
        if self._metrics and metrics:
            return losses, metrics
        return losses

    # ---- fit/evaluate/predict -------------------------------------------
    def _make_loader(self, data, batch_size, shuffle, num_workers, drop_last):
        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last)
        # (x, y) arrays
        arrays = [np.asarray(d) for d in _to_list(data)]
        ds = _NumpyDataset(arrays)
        return DataLoader(ds, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)

    def _wrap_prefetch(self, loader, prefetch):
        """Wrap `iter(loader)` in a `DevicePrefetcher` (io/prefetch.py)
        so batches are committed to device on a background thread while
        the current step computes — the async input pipeline ROADMAP
        item 4 plans. `prefetch=None` defers to the
        ``PADDLE_TPU_DATA_PREFETCH`` env switch (default on; the
        data_smoke CI gate holds the path loss-bit-exact vs sync).
        Returns (iterator, prefetcher-or-None) — the caller owns
        close(). A `DistributedBatchSampler`-driven loader under a
        'dp' mesh gets the sharded tier: each host commits only its
        local rows, assembled into NamedSharding global arrays."""
        from ..io import prefetch as _prefetch
        from ..io.sampler import DistributedBatchSampler

        on = prefetch if prefetch is not None else \
            _prefetch.prefetch_enabled()
        if not on:
            return iter(loader), None
        sharding = None
        wrap = False
        src = loader
        if isinstance(loader, DataLoader) and \
                isinstance(getattr(loader, "batch_sampler", None),
                           DistributedBatchSampler):
            from ..distributed import env as _env

            mesh = _env.get_mesh()
            if mesh is not None and "dp" in mesh.axis_names and \
                    mesh.shape["dp"] > 1:
                sharding = "dp"
                from ..io.dataloader import (
                    default_collate_fn, numpy_collate_or_default,
                )

                if loader.collate_fn is default_collate_fn:
                    # collate to RAW numpy for the sharded tier: the
                    # default collate's eager Tensor construction would
                    # commit each leaf to the local device only for the
                    # global assembly to haul it back — numpy in, ONE
                    # host→device commit per leaf out
                    src = DataLoader(
                        loader.dataset,
                        batch_sampler=loader.batch_sampler,
                        num_workers=loader.num_workers,
                        collate_fn=numpy_collate_or_default,
                        timeout=loader.timeout)
                    wrap = True
        pf = _prefetch.DevicePrefetcher(
            iter(src), timeout=getattr(loader, "timeout", 0) or None,
            sharding=sharding, wrap_tensors=wrap)
        return iter(pf), pf

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, prefetch=None):
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers, drop_last)
        eval_loader = self._make_loader(eval_data, batch_size, False,
                                        num_workers, False)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, log_freq=log_freq,
                                save_freq=save_freq, save_dir=save_dir,
                                verbose=verbose,
                                metrics=self._metrics_name())
        from .callbacks import LRScheduler as _LRCb

        # if the user installed an LRScheduler callback, it owns stepping
        user_steps_lr = any(isinstance(c, _LRCb) for c in cbks.callbacks)
        cbks.on_begin("train")
        self.stop_training = False
        it = 0
        logs = {}
        acc_k = max(1, int(accumulate_grad_batches))
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            self._reset_metrics()
            logs = {}
            # manual iteration so the loader's next() is measurable:
            # "step time waiting on data" is the input-pipeline gauge
            # the async staging below must drive toward zero. With the
            # prefetcher on, next() pops an already-device-committed
            # batch staged while the PREVIOUS step computed.
            data_iter, pf = self._wrap_prefetch(loader, prefetch)
            step = 0
            try:
                while True:
                    w0 = time.time()
                    t0 = time.perf_counter()
                    try:
                        batch = next(data_iter)
                    except StopIteration:
                        break
                    self._note_data_wait(time.perf_counter() - t0, w0)
                    cbks.on_batch_begin("train", step, logs)
                    xs, ys = self._split_batch(batch)
                    with _tracing.span("train_batch", "compute",
                                       epoch=epoch, step=step):
                        res = self.train_batch(xs, ys,
                                               update=(step + 1) % acc_k == 0)
                    logs = self._res_to_logs(res, step, batch_size)
                    with _tracing.span("callbacks", "callback"):
                        cbks.on_batch_end("train", step, logs)
                    it += 1
                    step += 1
                    if num_iters is not None and it >= num_iters:
                        self.stop_training = True
                    if self.stop_training:
                        # honored PER BATCH, not just at epoch
                        # boundaries: a callback stopping mid-epoch
                        # (ResilienceCallback escalation/stall) must not
                        # grind through the rest of a long or streaming
                        # epoch
                        break
            finally:
                if pf is not None:
                    pf.close()
            sch = self._optimizer._learning_rate
            if hasattr(sch, "step") and not isinstance(sch, float) and \
                    not user_steps_lr:
                from ..optimizer.lr import ReduceOnPlateau

                if not isinstance(sch, ReduceOnPlateau):
                    sch.step()
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self._run_eval(eval_loader, cbks, batch_size,
                                           prefetch=prefetch)
                logs.update({"eval_" + k: v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
        cbks.on_end("train", logs)
        return self

    def _note_data_wait(self, seconds, wall_start):
        """Input-pipeline visibility: per-batch loader wait as a
        histogram + last-value gauge (printed by profiler.summary) and
        a timeline span emitted from the SAME measurement — so
        `tracing.reconcile_with_metrics` can hold the two accountable
        to each other."""
        try:
            _telemetry.histogram(
                "paddle_tpu_data_wait_seconds",
                "train step time spent waiting on the input pipeline"
            ).observe(seconds)
            _telemetry.gauge(
                "paddle_tpu_data_wait_seconds_last",
                "last train batch's input-pipeline wait").set(seconds)
        except Exception:  # noqa: BLE001 — telemetry must never kill fit
            pass
        _tracing.emit_span("data_wait", "data", wall_start, seconds)

    def _run_eval(self, loader, cbks, batch_size, prefetch=None):
        self._reset_metrics()
        cbks.on_begin("eval")
        logs = {}
        data_iter, pf = self._wrap_prefetch(loader, prefetch)
        try:
            for step, batch in enumerate(data_iter):
                cbks.on_batch_begin("eval", step, logs)
                xs, ys = self._split_batch(batch)
                res = self.eval_batch(xs, ys)
                logs = self._res_to_logs(res, step, batch_size)
                cbks.on_batch_end("eval", step, logs)
        finally:
            if pf is not None:
                pf.close()
        cbks.on_end("eval", logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, prefetch=None):
        loader = self._make_loader(eval_data, batch_size, False, num_workers,
                                   False)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(callbacks, model=self, epochs=1,
                                steps=steps, log_freq=log_freq,
                                verbose=verbose,
                                metrics=self._metrics_name())
        logs = self._run_eval(loader, cbks, batch_size, prefetch=prefetch)
        out = {}
        if "loss" in logs:
            out["loss"] = logs["loss"]
        for m in self._metrics:
            for n, v in zip(_to_list(m.name()), _to_list(m.accumulate())):
                out[n] = v
        return out

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, num_workers,
                                   False)
        outputs = []
        for batch in loader:
            xs, _ = self._split_batch(batch, has_label=False)
            outs = self.predict_batch(xs)
            outputs.append([o.numpy() for o in outs])
        n_out = len(outputs[0])
        per_out = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            per_out = [np.concatenate(o, axis=0) for o in per_out]
        return per_out

    def _forward_arity(self):
        import inspect

        try:
            sig = inspect.signature(self.network.forward)
        except (TypeError, ValueError):
            return 1
        n = 0
        for p in sig.parameters.values():
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD) and \
                    p.default is p.empty:
                n += 1
            elif p.kind == p.VAR_POSITIONAL:
                return None  # *args: take everything
        return n

    def _split_batch(self, batch, has_label=True):
        batch = batch if isinstance(batch, (list, tuple)) else [batch]
        n_in = len(_to_list(self._inputs))
        if not n_in:
            arity = self._forward_arity()
            n_in = len(batch) if arity is None else min(arity, len(batch))
        xs = list(batch[:n_in])
        ys = list(batch[n_in:]) or None
        return xs, ys

    def _res_to_logs(self, res, step, batch_size):
        logs = {"step": step, "batch_size": batch_size}
        if isinstance(res, tuple):
            losses, metrics = res
        else:
            losses, metrics = res, []
        if losses:
            logs["loss"] = losses[0] if len(losses) == 1 else losses
        for m, r in zip(self._metrics, metrics):
            for n, v in zip(_to_list(m.name()), _to_list(r)):
                logs[n] = float(v)
        return logs

    def _metrics_name(self):
        out = ["loss"]
        for m in self._metrics:
            out.extend(_to_list(m.name()))
        return out

    def _reset_metrics(self):
        for m in self._metrics:
            m.reset()

    # ---- persistence -----------------------------------------------------
    def save(self, path, training=True):
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            sd = self._optimizer.state_dict()
            if self._engine._opt_states is not None:
                sd["_jit_states"] = {
                    str(k): {kk: np.asarray(vv) for kk, vv in v.items()}
                    for k, v in self._engine._opt_states.items()}
            _save(sd, path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        self.network.set_state_dict(_load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and os.path.exists(opt_path) and \
                self._optimizer is not None:
            sd = _load(opt_path)
            jit_states = sd.pop("_jit_states", None)
            self._optimizer.set_state_dict(sd)
            if jit_states is not None:
                # _load wraps leaf arrays in Tensor; unwrap before
                # jnp.asarray (a Tensor is a pytree node, not an array)
                self._engine._opt_states = {
                    int(k): {kk: jnp.asarray(
                        vv._value if isinstance(vv, Tensor) else vv)
                        for kk, vv in v.items()}
                    for k, v in jit_states.items()}
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary

        return _summary(self.network, input_size, dtypes=dtype)


class _NumpyDataset(Dataset):
    def __init__(self, arrays):
        self.arrays = arrays

    def __len__(self):
        return len(self.arrays[0])

    def __getitem__(self, i):
        return tuple(a[i] for a in self.arrays)
