"""paddle.flops — per-layer FLOP counting.

Reference: python/paddle/hapi/dynamic_flops.py:25 (flops/dynamic_flops) —
same counting formulas (convNd = out_numel * (Cin/groups * prod(k) + bias),
linear = in_features * out_numel, eval-mode BN = 2 * numel, …) driven by
forward-post hooks over leaf layers. An XLA-precise alternative is exposed
as `hlo_flops` (compiled-program cost analysis), which the reference has no
equivalent of.
"""
from __future__ import annotations

import numpy as np

__all__ = ["flops", "hlo_flops"]


def _numel(shape):
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _count_convnd(m, x, y):
    kernel_ops = _numel(m.weight.shape[2:])
    bias_ops = 1 if getattr(m, "bias", None) is not None else 0
    in_ch = x[0].shape[1]
    groups = getattr(m, "_groups", 1)
    return _numel(y.shape) * (in_ch / groups * kernel_ops + bias_ops)


def _count_linear(m, x, y):
    return m.weight.shape[0] * _numel(y.shape)


def _count_bn(m, x, y):
    return 0 if m.training else 2 * _numel(x[0].shape)


def _count_leaky_relu(m, x, y):
    return _numel(x[0].shape)


def _count_avgpool(m, x, y):
    return _numel(y.shape)


def _count_adap_avgpool(m, x, y):
    kernel = np.array(x[0].shape[2:]) // np.array(y.shape[2:])
    return (int(np.prod(kernel)) + 1) * _numel(y.shape)


def _count_zero(m, x, y):
    return 0


def _register_hooks():
    from .. import nn

    return {
        nn.Conv1D: _count_convnd,
        nn.Conv2D: _count_convnd,
        nn.Conv3D: _count_convnd,
        nn.Conv1DTranspose: _count_convnd,
        nn.Conv2DTranspose: _count_convnd,
        nn.Conv3DTranspose: _count_convnd,
        nn.BatchNorm1D: _count_bn,
        nn.BatchNorm2D: _count_bn,
        nn.BatchNorm3D: _count_bn,
        nn.BatchNorm: _count_bn,
        nn.ReLU: _count_zero,
        nn.ReLU6: _count_zero,
        nn.LeakyReLU: _count_leaky_relu,
        nn.Linear: _count_linear,
        nn.Dropout: _count_zero,
        nn.AvgPool1D: _count_avgpool,
        nn.AvgPool2D: _count_avgpool,
        nn.AvgPool3D: _count_avgpool,
        nn.AdaptiveAvgPool1D: _count_adap_avgpool,
        nn.AdaptiveAvgPool2D: _count_adap_avgpool,
        nn.AdaptiveAvgPool3D: _count_adap_avgpool,
    }


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Count FLOPs of `net` on a synthetic input of `input_size`.

    Returns the total (int). print_detail renders a per-layer table.
    """
    from .. import tensor as T
    from ..core.autograd import no_grad
    from ..nn.layer.layers import Layer

    if not isinstance(net, Layer):
        from ..static import Program

        if isinstance(net, Program):
            raise NotImplementedError(
                "static Program flops: trace the program's layer instead")
        return -1

    table = _register_hooks()
    if custom_ops:
        table.update(custom_ops)

    rows = []
    total = {"ops": 0, "params": 0}
    handles = []
    counted_params = set()  # layer ids — a reused layer's params count once

    def add_hook(m):
        if list(m.children()):
            return
        fn = table.get(type(m))

        def post(layer, inp, out, _fn=fn):
            inp = inp if isinstance(inp, (list, tuple)) else (inp,)
            out0 = out[0] if isinstance(out, (list, tuple)) else out
            ops = int(abs(_fn(layer, inp, out0))) if _fn is not None else 0
            params = sum(_numel(p.shape) for p in layer.parameters())
            rows.append((layer.full_name() if hasattr(layer, "full_name")
                         else type(layer).__name__,
                         list(inp[0].shape), list(out0.shape), params, ops))
            total["ops"] += ops
            if id(layer) not in counted_params:
                counted_params.add(id(layer))
                total["params"] += params

        handles.append(m.register_forward_post_hook(post))

    layers = net.sublayers(include_self=True)
    saved_modes = [l.training for l in layers]
    net.eval()
    net.apply(add_hook)
    try:
        with no_grad():
            net(T.randn(list(input_size)))
    finally:
        for h in handles:
            h.remove()
        for l, flag in zip(layers, saved_modes):
            l.training = flag

    if print_detail:
        hdr = ("Layer Name", "Input Shape", "Output Shape", "Params", "Flops")
        widths = [max(len(str(r[i])) for r in rows + [hdr])
                  for i in range(5)]
        line = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
        print(line)
        print("|" + "|".join(f" {str(h):^{w}} " for h, w in zip(hdr, widths))
              + "|")
        print(line)
        for r in rows:
            print("|" + "|".join(f" {str(c):^{w}} "
                                 for c, w in zip(r, widths)) + "|")
        print(line)
        print(f"Total Flops: {total['ops']}     "
              f"Total Params: {total['params']}")
    return total["ops"]


def hlo_flops(fn, *example_args):
    """XLA-exact FLOPs: compile `fn` and read the HLO cost analysis."""
    import jax

    from ..core import dispatch as _dispatch

    # `fn` is typically a layer forward: the .lower() trace dispatches
    # its ops — keep them out of the per-op jit cache (tracelint
    # suspend-audit)
    with _dispatch.suspend():  # fuselint: ok[FL004] flops counting lowers the model once, off the step loop
        compiled = jax.jit(fn).lower(*example_args).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    return int(cost.get("flops", -1)) if cost else -1
