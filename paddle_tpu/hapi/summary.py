"""Model summary (reference: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["summary"]


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    """Prints per-layer output shapes + param counts; returns totals."""
    from .. import tensor as T

    hooks = []
    rows = []

    def mk_hook(name):
        def hook(layer, inputs, outputs):
            outs = outputs if isinstance(outputs, (list, tuple)) else \
                [outputs]
            shapes = [list(o.shape) for o in outs if isinstance(o, Tensor)]
            n_params = sum(p.size for p in layer._parameters.values()
                           if p is not None)
            rows.append((name, type(layer).__name__, shapes, n_params))
        return hook

    for name, layer in net.named_sublayers():
        if not layer._sub_layers:  # leaves only
            hooks.append(layer.register_forward_post_hook(mk_hook(name)))

    if input is not None:
        x = input
        net(*x) if isinstance(x, (list, tuple)) else net(x)
    else:
        sizes = input_size if isinstance(input_size, list) and \
            isinstance(input_size[0], (list, tuple)) else [input_size]
        xs = [T.zeros(list(s), dtype=d) for s, d in
              zip(sizes, (dtypes if isinstance(dtypes, (list, tuple))
                          else [dtypes] * len(sizes)))]
        net(*xs)
    for h in hooks:
        h.remove()

    total = sum(p.size for p in net.parameters())
    trainable = sum(p.size for p in net.parameters()
                    if not p.stop_gradient)
    line = "-" * 80
    print(line)
    print(f"{'Layer (type)':<38}{'Output Shape':<26}{'Param #':>14}")
    print(line)
    for name, tname, shapes, n in rows:
        shape_s = str(shapes[0]) if shapes else "-"
        print(f"{name + ' (' + tname + ')':<38}{shape_s:<26}{n:>14,}")
    print(line)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}
