"""paddle.linalg namespace (reference: python/paddle/linalg.py — a re-export
surface over tensor/linalg)."""
from .tensor.linalg import *  # noqa: F401,F403
from .tensor.linalg import __all__  # noqa: F401
