"""Dygraph-to-static AST conversion (subset).

Reference: python/paddle/jit/dy2static/program_translator.py + the
convert_ifelse / convert_while_loop transformers in jit/dy2static/
convert_operators.py. The reference rewrites Python control flow whose
predicate is a Tensor into cond/while ops so one static program serves all
branches; under plain tracing such code raises TracerBoolConversionError.

This implements the load-bearing subset:
  * `if`/`elif`/`else` with tensor predicates  -> lax.cond via
    static.nn.cond, with assigned-name join analysis
  * `while` with tensor predicates             -> lax.while_loop via
    static.nn.while_loop, body-assigned names as loop carries
Python-valued predicates keep exact eager semantics (runtime dispatch).
Statements a structured XLA region cannot express (return/break/continue
inside the branch, `global`/`nonlocal`) leave the statement untransformed.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap

import numpy as np

__all__ = ["convert_to_static", "convert_cond", "convert_while"]

_HELPER = "__paddle_jst"


def _assigned_names(nodes):
    """Names bound by simple assignments in a statement list (recursive)."""
    out = []

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, (ast.Store,)):
                if node.id not in out:
                    out.append(node.id)

        def visit_FunctionDef(self, node):  # don't descend into nested defs
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_AugAssign(self, node):
            if isinstance(node.target, ast.Name) \
                    and node.target.id not in out:
                out.append(node.target.id)
            self.generic_visit(node)

    v = V()
    for n in nodes:
        v.visit(n)
    return out


def _has_escape(nodes):
    """return/break/continue/global/nonlocal anywhere in the block
    (nested function bodies excluded — they are their own scope)?"""
    found = [False]

    class V(ast.NodeVisitor):
        def visit_Return(self, node):
            found[0] = True

        def visit_Break(self, node):
            found[0] = True

        def visit_Continue(self, node):
            found[0] = True

        def visit_Global(self, node):
            found[0] = True

        def visit_Nonlocal(self, node):
            found[0] = True

        def visit_FunctionDef(self, node):
            pass  # don't descend

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

    v = V()
    for n in nodes:
        v.visit(n)
    return found[0]


def _prestate(names):
    """`(HELPER.get(lambda: a), HELPER.get(lambda: b))` — current values of
    the join names, UNDEF where a name is not yet bound (body-local
    temporaries, branch-introduced names)."""
    def one(n):
        return ast.Call(
            func=ast.Attribute(value=ast.Name(id=_HELPER, ctx=ast.Load()),
                               attr="get", ctx=ast.Load()),
            args=[ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=ast.Name(id=n, ctx=ast.Load()))],
            keywords=[])

    return ast.Tuple(elts=[one(n) for n in names], ctx=ast.Load())


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._n = 0

    def _uid(self):
        self._n += 1
        return self._n

    # -- if / elif / else ---------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)  # inner blocks first (handles elif chains)
        if _has_escape(node.body) or _has_escape(node.orelse):
            return node
        names = _assigned_names(node.body + node.orelse)
        uid = self._uid()
        tname, fname = f"__jst_true_{uid}", f"__jst_false_{uid}"
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
            ctx=ast.Load()))
        # branches take the join names as parameters so read-then-write
        # (`y = y + 1`) sees the pre-branch value
        params = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in names],
            kwonlyargs=[], kw_defaults=[], defaults=[])

        def branch(fn_name, body):
            return ast.FunctionDef(
                name=fn_name, args=params,
                body=(body or [ast.Pass()]) + [ret],
                decorator_list=[])

        call = ast.Call(
            func=ast.Attribute(value=ast.Name(id=_HELPER, ctx=ast.Load()),
                               attr="cond", ctx=ast.Load()),
            args=[node.test, ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  _prestate(names)], keywords=[])
        assign = (ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in names],
                ctx=ast.Store())], value=call)
            if names else ast.Expr(value=call))
        return [branch(tname, node.body), branch(fname, node.orelse),
                assign]

    # -- for i in range(...) ------------------------------------------------
    def visit_For(self, node):
        """Desugar `for i in range(...)` into a while so tensor-valued
        bounds trace to lax.while_loop (reference dy2static converts
        range loops the same way); every other `for` stays Python.

        Escapes (break/continue/return) keep the original For: the
        desugared body would run `continue` WITHOUT the index increment.

        An INTERNAL counter drives the loop; the target is assigned from
        it at the top of each pass, so after a non-empty loop the target
        holds the last yielded value (start+(n-1)*step), matching
        Python — not one-past-the-end — and a body that reassigns the
        loop var still iterates the full range (the counter, not the
        target, is carried). Known divergence: an empty range leaves
        the loop var bound to `start` here, where Python leaves it
        unbound."""
        self.generic_visit(node)
        it = node.iter
        if (node.orelse or not isinstance(node.target, ast.Name)
                or not isinstance(it, ast.Call)
                or not isinstance(it.func, ast.Name)
                or it.func.id != "range" or it.keywords
                or not 1 <= len(it.args) <= 3
                or any(isinstance(a, ast.Starred) for a in it.args)
                or _has_escape(node.body)):
            return node
        target = node.target.id
        uid = self._uid()
        if len(it.args) == 1:
            start, stop = ast.Constant(value=0), it.args[0]
            step = ast.Constant(value=1)
        else:
            start, stop = it.args[0], it.args[1]
            step = it.args[2] if len(it.args) == 3 else ast.Constant(value=1)
        idx_n = f"__jst_fidx_{uid}"
        stop_n, step_n = f"__jst_fstop_{uid}", f"__jst_fstep_{uid}"
        # one validating call also keeps range()'s left-to-right argument
        # evaluation order and its TypeError/ValueError contract
        args_call = ast.Call(
            func=ast.Attribute(value=ast.Name(id=_HELPER, ctx=ast.Load()),
                               attr="range_args", ctx=ast.Load()),
            args=[start, stop, step], keywords=[])
        pre = [
            ast.Assign(targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store())
                      for n in (idx_n, stop_n, step_n)],
                ctx=ast.Store())], value=args_call),
            # binds the target pre-loop so the while carry is well-typed
            # (and documents the empty-range divergence: target = start)
            ast.Assign(targets=[ast.Name(id=target, ctx=ast.Store())],
                       value=ast.Name(id=idx_n, ctx=ast.Load())),
        ]
        test = ast.Call(
            func=ast.Attribute(value=ast.Name(id=_HELPER, ctx=ast.Load()),
                               attr="range_cond", ctx=ast.Load()),
            args=[ast.Name(id=idx_n, ctx=ast.Load()),
                  ast.Name(id=stop_n, ctx=ast.Load()),
                  ast.Name(id=step_n, ctx=ast.Load())],
            keywords=[])
        set_target = ast.Assign(
            targets=[ast.Name(id=target, ctx=ast.Store())],
            value=ast.Name(id=idx_n, ctx=ast.Load()))
        bump = ast.Assign(
            targets=[ast.Name(id=idx_n, ctx=ast.Store())],
            value=ast.BinOp(left=ast.Name(id=idx_n, ctx=ast.Load()),
                            op=ast.Add(),
                            right=ast.Name(id=step_n, ctx=ast.Load())))
        loop = ast.While(test=test, body=[set_target] + node.body + [bump],
                         orelse=[])
        out = self.visit_While(loop)
        return pre + (out if isinstance(out, list) else [out])

    # -- while --------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or node.orelse:
            return node
        names = _assigned_names(node.body)
        if not names:
            return node
        uid = self._uid()
        cname, bname = f"__jst_wcond_{uid}", f"__jst_wbody_{uid}"
        params = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=n) for n in names],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        cond_fn = ast.FunctionDef(
            name=cname, args=params,
            body=[ast.Return(value=node.test)], decorator_list=[])
        body_fn = ast.FunctionDef(
            name=bname, args=params,
            body=node.body + [ast.Return(value=ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
                ctx=ast.Load()))],
            decorator_list=[])
        call = ast.Call(
            func=ast.Attribute(value=ast.Name(id=_HELPER, ctx=ast.Load()),
                               attr="while_loop", ctx=ast.Load()),
            args=[ast.Name(id=cname, ctx=ast.Load()),
                  ast.Name(id=bname, ctx=ast.Load()),
                  _prestate(names)],
            keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in names],
                ctx=ast.Store())], value=call)
        return [cond_fn, body_fn, assign]


# ---- runtime dispatch helpers ---------------------------------------------
class _Undefined:
    """Placeholder for a join/carry name with no pre-statement binding
    (mirrors the reference's UndefinedVar): using it in tensor math raises
    naturally; assigning over it is the normal case."""

    def __repr__(self):
        return "<dy2static undefined>"


UNDEF = _Undefined()


def _get(thunk):
    try:
        return thunk()
    except NameError:  # includes free-variable-before-assignment
        return UNDEF


def _is_tensor_pred(pred):
    from ..core.tensor import Tensor

    return isinstance(pred, Tensor)


def convert_cond(pred, true_fn, false_fn, prestate=()):
    if _is_tensor_pred(pred):
        import jax

        from ..static.nn import cond as _cond

        if isinstance(pred._value, jax.core.Tracer) or \
                _in_static_mode():
            return _cond(pred, lambda: true_fn(*prestate),
                         lambda: false_fn(*prestate))
        pred = bool(pred._value)  # concrete eager value: exact semantics
    return true_fn(*prestate) if pred else false_fn(*prestate)


def convert_while(cond_fn, body_fn, loop_vars):
    probe = cond_fn(*loop_vars)
    if _is_tensor_pred(probe):
        import jax

        if isinstance(probe._value, jax.core.Tracer) or _in_static_mode():
            from ..static.nn import while_loop as _wl

            # body-local temporaries (UNDEF before the loop) are not loop
            # state — XLA can't carry them. They're recomputed inside the
            # body each iteration and stay UNDEF afterwards (using one
            # post-loop raises, loudly, instead of silently mis-tracing).
            live = [i for i, v in enumerate(loop_vars) if v is not UNDEF]
            if len(live) < len(loop_vars):
                def expand(vals_live):
                    full = [UNDEF] * len(loop_vars)
                    for i, v in zip(live, vals_live):
                        full[i] = v
                    return full

                def c2(*vals_live):
                    return cond_fn(*expand(vals_live))

                def b2(*vals_live):
                    res = body_fn(*expand(vals_live))
                    return [res[i] for i in live]

                out_live = _wl(c2, b2, [loop_vars[i] for i in live])
                return tuple(expand(list(out_live)))
            out = _wl(cond_fn, body_fn, list(loop_vars))
            return tuple(out)
        # concrete eager: plain python loop
        vals = tuple(loop_vars)
        while bool(cond_fn(*vals)._value):
            vals = tuple(body_fn(*vals))
        return vals
    vals = tuple(loop_vars)
    while cond_fn(*vals):
        vals = tuple(body_fn(*vals))
    return vals


def convert_range_args(start, stop, step):
    """Validate desugared range() arguments with Python's own contract
    (TypeError on non-integral, ValueError on step==0); tensors pass
    through for traced bounds."""
    import operator

    def check(v, name):
        if _is_tensor_pred(v):
            return v
        try:  # Python's own contract: bools and __index__ types pass
            return operator.index(v)
        except TypeError:
            raise TypeError(
                f"'{type(v).__name__}' object cannot be interpreted as an "
                f"integer (range() {name})") from None

    start, stop, step = (check(start, "start"), check(stop, "stop"),
                         check(step, "step"))
    if not _is_tensor_pred(step) and step == 0:
        raise ValueError("range() arg 3 must not be zero")
    return start, stop, step


def convert_range_cond(i, stop, step):
    """`i` still inside range(start, stop, step)? Sign-aware, tensor-aware
    (the desugared `for` uses this as its while predicate)."""
    if not any(_is_tensor_pred(v) for v in (i, stop, step)):
        return (step > 0 and i < stop) or (step < 0 and i > stop)
    if not _is_tensor_pred(step):  # static step: pick the branch directly
        return (i < stop) if step > 0 else (i > stop)
    pos = (step > 0) & (i < stop)
    neg = (step < 0) & (i > stop)
    return pos | neg


class _Helper:
    cond = staticmethod(convert_cond)
    while_loop = staticmethod(convert_while)
    range_cond = staticmethod(convert_range_cond)
    range_args = staticmethod(convert_range_args)
    get = staticmethod(_get)
    UNDEF = UNDEF


class _Scope(dict):
    """Globals for the re-exec'd function: writes stay local (the module's
    own binding of the function name must not be touched), reads fall
    through LIVE to the original globals and closure cells — later
    rebindings in the enclosing scope keep working (LOAD_GLOBAL honors
    dict-subclass __missing__)."""

    def __init__(self, base, cells):
        super().__init__()
        self._base = base
        self._cells = cells  # name -> cell

    def __missing__(self, key):
        if key in self._cells:
            return self._cells[key].cell_contents
        return self._base[key]


def convert_to_static(fn):
    """Rewrite tensor-predicate control flow in `fn`; returns the original
    callable untouched when the source is unavailable or unsupported
    (bound methods, builtins, exec-defined functions, escape statements)."""
    if inspect.ismethod(fn) or not inspect.isfunction(fn):
        # re-exec'ing a bound method would drop its `self` binding
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
        fdef = tree.body[0]
        # strip decorators (@to_static would recurse infinitely)
        fdef.decorator_list = []
        new = _ControlFlowTransformer().visit(tree)
        ast.fix_missing_locations(new)
        from . import _code_level

        if _code_level > 0:
            print(f"--- dy2static: {fn.__name__} ---")
            print(ast.unparse(new))
        code = compile(new, filename=f"<dy2static {fn.__name__}>",
                       mode="exec")
        cells = dict(zip(fn.__code__.co_freevars, fn.__closure__ or ()))
        scope = _Scope(fn.__globals__, cells)
        scope[_HELPER] = _Helper
        exec(code, scope)  # noqa: S102 — compiling our own transform
        out = scope[fn.__name__]
        out = functools.wraps(fn)(out)
        out.__wrapped_by_dy2static__ = True
        return out
    except (OSError, TypeError, SyntaxError, KeyError):
        return fn


def _in_static_mode():
    from ..framework.mode import in_static_mode

    return in_static_mode()
